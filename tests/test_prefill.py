"""Chunked prompt prefill: same cache, same token streams as the
token-at-a-time path (the reference's only prompt handling)."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.runtime.generate import (Engine, generate,
                                                    generate_fast,
                                                    run_chunked_prefill)
from distributed_llama_tpu.runtime.sampling import Sampler

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=300, seq_len=16)


class _IdTokenizer:
    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"?"


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=9, scale=0.3)


def _sampler(seed=77, temp=0.9):
    return Sampler(SPEC.vocab_size, temperature=temp, topp=0.9, seed=seed)


@pytest.mark.parametrize("chunk", [2, 4, 128])
def test_prefill_cache_matches_stepwise(params, chunk):
    """Engine.prefill == the same tokens through T=1 steps: identical live
    cache prefix and identical next-step logits."""
    import jax.numpy as jnp

    tokens = [1, 9, 14, 23, 5, 40, 7]
    eng_a = Engine(SPEC, params)
    for p, t in enumerate(tokens):
        eng_a.infer(t, p)
    la = eng_a.infer(77, len(tokens))

    eng_b = Engine(SPEC, params)
    eng_b.prefill(tokens, 0, chunk=chunk)
    lb = eng_b.infer(77, len(tokens))

    n = len(tokens) + 1
    np.testing.assert_allclose(np.asarray(eng_b.cache.k[:, :n]),
                               np.asarray(eng_a.cache.k[:, :n]),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(lb, la, rtol=2e-5, atol=2e-5)


def test_prefill_near_seq_len_tail(params):
    """A padded chunk that would cross seq_len must not shift writes back
    over real positions (the dynamic_update_slice clamp hazard): prefill to
    within a chunk of seq_len and compare against stepwise."""
    tokens = list(np.random.default_rng(3).integers(
        3, 200, SPEC.seq_len - 2))  # 14 tokens, chunk 4 -> padded tail would
    tokens[0] = 1                   # reach pos 16 > seq_len without the guard
    eng_a = Engine(SPEC, params)
    for p, t in enumerate(tokens):
        eng_a.infer(t, p)
    eng_b = Engine(SPEC, params)
    eng_b.prefill(tokens, 0, chunk=4)
    np.testing.assert_allclose(
        np.asarray(eng_b.cache.k[:, :len(tokens)]),
        np.asarray(eng_a.cache.k[:, :len(tokens)]), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("temp", [0.0, 0.9])
def test_generate_with_prefill_matches_plain(params, temp):
    tok = _IdTokenizer()
    ref, _ = generate(Engine(SPEC, params), tok, _sampler(temp=temp),
                      "abcde", steps=12, quiet=True)
    got, _ = generate(Engine(SPEC, params), tok, _sampler(temp=temp),
                      "abcde", steps=12, quiet=True, prefill_chunk=4)
    assert got == ref


@pytest.mark.parametrize("temp", [0.0, 0.9])
def test_generate_fast_with_prefill_matches_plain(params, temp):
    tok = _IdTokenizer()
    ref, sref = generate_fast(Engine(SPEC, params), tok, _sampler(temp=temp),
                              "abcde", steps=12, quiet=True)
    got, sgot = generate_fast(Engine(SPEC, params), tok,
                              _sampler(temp=temp), "abcde", steps=12,
                              quiet=True, prefill_chunk=4)
    assert got == ref
    # resumability anchors must agree too (same final pos/token)
    assert (sgot.final_pos, sgot.final_token) == (sref.final_pos,
                                                  sref.final_token)


def test_prefill_early_bos_rng_rewind(params):
    """When the fused chain samples an early BOS, the sampler's RNG must end
    at the same state as the per-step loop — with prefill active, the coin
    accounting must use the CHAIN-generated count, not the echoed total."""
    tok = _IdTokenizer()
    # find a seed whose per-step run stops early on a sampled BOS
    # (multinomial walk over a near-uniform vocab: a small first coin lands
    # on token 1); steps > prompt so prefill engages
    found = None
    for seed in range(300):
        s = Sampler(SPEC.vocab_size, temperature=0.9, topp=1.0, seed=seed)
        eng = Engine(SPEC, params)
        out, st = generate(eng, tok, s, "abc", steps=12, quiet=True)
        if len(out) < 12 - 1 and st.final_token == 1:
            found = (seed, out, s.rng.state)
            break
    assert found is not None, "no early-BOS seed in range — widen the scan"
    seed, ref_out, ref_state = found

    s2 = Sampler(SPEC.vocab_size, temperature=0.9, topp=1.0, seed=seed)
    out2, _ = generate_fast(Engine(SPEC, params), tok, s2, "abc", steps=12,
                            quiet=True, prefill_chunk=2)
    assert out2 == ref_out
    assert s2.rng.state == ref_state


def test_prefill_gates_off_on_midstream_bos(params):
    """A prompt whose encoding contains BOS mid-stream stops the per-token
    loop; prefill must fall back so the truncated output is reproduced."""

    class _MidBos:
        def encode(self, text, bos=True, eos=False):
            return [1, 9, 1, 14, 23]  # BOS at index 2

        def decode_piece(self, prev, tok):
            return b"?"

    tok = _MidBos()
    ref, _ = generate(Engine(SPEC, params), tok, _sampler(), "x", steps=12,
                      quiet=True)
    got, _ = generate(Engine(SPEC, params), tok, _sampler(), "x", steps=12,
                      quiet=True, prefill_chunk=2)
    assert got == ref
    gotf, _ = generate_fast(Engine(SPEC, params), tok, _sampler(), "x",
                            steps=12, quiet=True, prefill_chunk=2)
    assert gotf == ref


@pytest.mark.parametrize("sp,tp", [(1, 2), (2, 1), (2, 2)])
def test_generate_prefill_on_sharded_engine(params, sp, tp):
    """--prefill-chunk on a sharded (sp/tp) engine: same stream as the
    sharded per-token path (the sp cache update handles T>1 windows that
    straddle chunk boundaries — parallel/ring.update_sp_cache)."""
    from distributed_llama_tpu.parallel import make_mesh

    tok = _IdTokenizer()
    mesh = make_mesh(sp=sp, tp=tp)
    ref, _ = generate(Engine(SPEC, params, mesh=mesh), tok, _sampler(),
                      "abcde", steps=12, quiet=True)
    got, _ = generate(Engine(SPEC, params, mesh=mesh), tok, _sampler(),
                      "abcde", steps=12, quiet=True, prefill_chunk=4)
    assert got == ref


def test_prefill_q80_buffer_parity(params):
    """Chunked prefill under the Q80 activation-wire mode: the quantize
    cut points apply identically in T>1 windows, so prefill == stepwise."""
    import dataclasses

    from distributed_llama_tpu.ops.quants import FloatType

    spec_q80 = dataclasses.replace(SPEC, buffer_float_type=FloatType.Q80)
    tok = _IdTokenizer()
    ref, _ = generate(Engine(spec_q80, params), tok, _sampler(), "abcde",
                      steps=12, quiet=True)
    got, _ = generate(Engine(spec_q80, params), tok, _sampler(), "abcde",
                      steps=12, quiet=True, prefill_chunk=4)
    assert got == ref


def test_prefill_gates_off_when_prompt_exceeds_steps(params):
    """Prompt longer than steps: prefill must not engage (the per-token
    path's forced-echo output semantics are load-bearing there)."""
    tok = _IdTokenizer()
    long = "abcdefghij"  # 11 tokens with BOS, steps 6
    ref, _ = generate(Engine(SPEC, params), tok, _sampler(), long, steps=6,
                      quiet=True)
    got, _ = generate(Engine(SPEC, params), tok, _sampler(), long, steps=6,
                      quiet=True, prefill_chunk=4)
    assert got == ref


def test_fast_prefill_bf16_tolerance_and_isolation():
    """--fast-prefill: the bf16 prefill program fills the cache within a
    pinned tolerance of the parity program, touches ONLY T>8 chunks (the
    T=1 tail and decode keep the parity forward), and the same-engine
    decode path object is unchanged (VERDICT r1 #7)."""
    import numpy as np

    import jax.numpy as jnp

    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.runtime.generate import Engine

    params = synth_params(SPEC, q40=False, seed=3, scale=0.3)
    tokens = list(np.random.default_rng(1).integers(2, SPEC.vocab_size,
                                                    12))

    ref = Engine(SPEC, params)
    ref.prefill([int(t) for t in tokens], 0, chunk=12)
    fast = Engine(SPEC, params, fast_prefill=True)
    assert fast._fwd_prefill is not None and fast._fwd_prefill is not fast._fwd
    fast.prefill([int(t) for t in tokens], 0, chunk=12)

    k_ref = np.asarray(ref.cache.k[:, :12])
    k_fast = np.asarray(fast.cache.k[:, :12])
    # pinned bf16 drift bound, relative to activation scale: bf16 mantissa
    # gives ~2^-8 per op; observed ~1.2e-2 relative over 2 layers — pin ~2x
    scale = np.abs(k_ref).max()
    drift = np.abs(k_ref - k_fast).max() / scale
    assert 0 < drift < 2.5e-2
    # decode after prefill still runs the parity program (same jitted fn)
    lg_ref = ref.infer(int(tokens[-1]) % SPEC.vocab_size, 12)
    lg_fast = fast.infer(int(tokens[-1]) % SPEC.vocab_size, 12)
    rel = np.abs(lg_ref - lg_fast).max() / max(np.abs(lg_ref).max(), 1e-9)
    assert rel < 2.5e-2  # only prefilled-cache drift remains


def test_fused_prefill_loop_matches_per_chunk_dispatch():
    """>=2 full windows at chunk>8 run as ONE device program (fori_loop
    over windows, cache donated — Engine._prefill_loop). Cache and
    next-step logits must match the per-chunk host dispatch exactly
    (same per-window program, f32)."""
    import jax.numpy as jnp

    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=300, seq_len=64)
    params = synth_params(spec, q40=False, seed=11, scale=0.3)
    tokens = list(np.random.default_rng(3).integers(2, 290, 41))  # 3x12+5

    eng_a = Engine(spec, params)
    eng_a.prefill(tokens, 0, chunk=12)  # fused: 3 full windows + tail 5
    la = eng_a.infer(7, len(tokens))

    eng_b = Engine(spec, params)  # reference: windows dispatched one by one
    for lo in range(0, 36, 12):
        _, eng_b.cache = eng_b._fwd(eng_b.params, eng_b.cache,
                                    jnp.asarray(tokens[lo:lo + 12],
                                                jnp.int32), jnp.int32(lo))
    run_chunked_prefill(
        lambda part, start: setattr(
            eng_b, "cache",
            eng_b._fwd(eng_b.params, eng_b.cache,
                       jnp.asarray(part, jnp.int32),
                       jnp.int32(start))[1]),
        tokens[36:], 36, 12, spec.seq_len)
    lb = eng_b.infer(7, len(tokens))

    n = len(tokens) + 1
    np.testing.assert_allclose(np.asarray(eng_a.cache.k[:, :n]),
                               np.asarray(eng_b.cache.k[:, :n]),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(la, lb, rtol=1e-6, atol=1e-6)


def test_blockwise_prefill_attention_matches_dense(monkeypatch):
    """T>8 prefill attention via the blockwise live-prefix while_loop
    (DLLAMA_PREFILL_ATTN=block, the default) must match the dense
    masked-plane path within online-softmax reassociation noise."""
    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=300, seq_len=64)
    params = synth_params(spec, q40=False, seed=21, scale=0.3)
    tokens = list(np.random.default_rng(5).integers(2, 290, 48))

    out = {}
    for mode in ("block", "dense"):
        monkeypatch.setenv("DLLAMA_PREFILL_ATTN", mode)
        eng = Engine(spec, params)
        eng.prefill(tokens, 0, chunk=16)
        out[mode] = (np.asarray(eng.cache.k[:, :49]),
                     eng.infer(7, len(tokens)))
    np.testing.assert_allclose(out["block"][0], out["dense"][0],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(out["block"][1], out["dense"][1],
                               rtol=1e-5, atol=1e-5)


def test_prefill_attn_mode_rejects_typos(monkeypatch):
    monkeypatch.setenv("DLLAMA_PREFILL_ATTN", "blockwise")
    from distributed_llama_tpu.models.llama import _prefill_attn_mode

    with pytest.raises(ValueError, match="DLLAMA_PREFILL_ATTN"):
        _prefill_attn_mode()
