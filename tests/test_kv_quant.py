"""Q8-quantized KV pages (ISSUE 11): engine streams, prefill/sharing,
speculative rollback, memory-model pricing, and the serving surfaces.

Quantization genuinely changes logits, so the f32 bitwise gates move to
DISTRIBUTION-PINNED properties here: greedy q8 streams are deterministic
and scheduler-invisible (identical across per-step, fused-chain,
speculative, and prefill drivers, and across tp meshes), pinned stable
at Q8 vs the f32 streams on the CPU smoke model; pool accounting stays
leak-free under the same audit oracle as f32 paging.
"""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params

# q8 needs (n_kv/tp * head_size) % 32 == 0: head_size 32, n_kv 2 covers
# tp in {1, 2}
SPEC = TransformerSpec(dim=128, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)
REQS = [[1, 5, 9], [1, 5, 7, 11], [1, 3], [1, 5, 9, 2]]


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


def _run(params, steps=10, reqs=None, **kw):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    eng = ContinuousEngine(SPEC, params, slots=kw.pop("slots", 2),
                           temperature=kw.pop("temperature", 0.0),
                           topp=0.9, seed=3, page_size=kw.pop("page_size", 4),
                           kv_quant="q8", **kw)
    outs, st = eng.run(list(reqs or REQS), steps=steps)
    assert eng.audit_pages() == [], eng.audit_pages()
    return eng, outs, st


def test_q8_streams_scheduler_invisible_and_pinned_vs_f32(params):
    """Greedy q8 streams are identical across every scheduler driver
    (per-step, fused chains, speculative verify, admission prefill,
    slot-count changes) — scheduling and paging stay invisible — and on
    the CPU smoke model they are pinned equal to the f32 greedy streams
    (quantization noise below the greedy argmax margin here; the pin is
    the distribution-stability gate, not a universal claim)."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    _, base, _ = _run(params)
    for kw in ({"block_steps": 3}, {"spec_k": 3}, {"prefill_chunk": 2},
               {"slots": 4}):
        _, outs, _ = _run(params, **kw)
        assert outs == base, f"q8 stream drifted under {kw}"
    f32 = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=3, page_size=4)
    f32_outs, _ = f32.run(list(REQS), steps=10)
    assert base == f32_outs


def test_q8_streams_match_over_tp_mesh(params):
    """tp=2 q8 streams equal the single-chip q8 streams: per-shard
    quantization blocks are head-band aligned, so the sharded encoding
    is the single-chip encoding sliced."""
    import jax

    from distributed_llama_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    _, base, _ = _run(params)
    mesh = make_mesh(tp=2, devices=jax.devices()[:2])
    _, outs, _ = _run(params, mesh=mesh)
    assert outs == base


def test_q8_sampled_streams_deterministic(params):
    """Seeded sampled q8 streams replay exactly (the crash-recovery
    anchor: replay determinism is a function of (prompt, sampler, coin
    cursor, kv_quant) — the fingerprint pins the last one)."""
    _, a, _ = _run(params, temperature=0.8)
    _, b, _ = _run(params, temperature=0.8)
    assert a == b


def test_q8_prefix_sharing_and_prefill_share_pages(params):
    """A shared page-aligned system prompt hits the radix tree under q8
    exactly like f32 (the tree shares PAGES; their encoding is
    quantized but position-deterministic), and admission prefill's
    scatter does not disturb shared pages (they are scrap-redirected,
    so the first publisher's bytes survive)."""
    ps = 4
    sys_prefix = [1] + [7 + (i % 9) for i in range(2 * ps)]
    reqs = [sys_prefix + [3 + i, 5 + i] for i in range(4)]
    eng, outs, _ = _run(params, steps=14, reqs=reqs, page_size=ps,
                        prefill_chunk=ps, slots=2)
    a = eng.allocator
    assert a.prefix_hits >= 1
    assert a.tokens_saved >= 2 * ps
    # all rows share the prefix: identical prompts -> identical prefixes
    # of output (forced echo), and the engine replays deterministically
    eng2, outs2, _ = _run(params, steps=14, reqs=reqs, page_size=ps,
                          prefill_chunk=ps, slots=2)
    assert outs == outs2


def test_q8_speculative_rollback_returns_pages(params):
    """Speculative q8: rejected-draft pages return to the pool (the
    audit oracle runs inside _run) and the stream equals the spec-off
    q8 stream — losslessness holds relative to the q8 engine."""
    _, base, _ = _run(params, steps=12)
    _, outs, st = _run(params, steps=12, spec_k=4)
    assert outs == base
    assert st.steps <= 12 * len(REQS)  # verify dispatches, not per-token


def test_q8_requires_page_size(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    with pytest.raises(ValueError, match="kv-page-size|page_size"):
        ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                         seed=3, kv_quant="q8")
    with pytest.raises(ValueError, match="f32|q8"):
        ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                         seed=3, page_size=4, kv_quant="int4")


def test_q8_rejects_unaligned_kv_width(params):
    """(n_kv/tp * head_size) % 32 != 0 must refuse at construction with
    the block-granularity constraint named (tp factories and the
    single-chip init share the rule)."""
    from distributed_llama_tpu.models.llama import init_cache_paged_q8
    from distributed_llama_tpu.parallel.tp import validate_kv_quant

    bad = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                          n_kv_heads=2, vocab_size=128, seq_len=16)
    assert bad.head_size * bad.n_kv_heads == 32  # tp=1 fine...
    validate_kv_quant(bad, 1, "q8")
    with pytest.raises(ValueError, match="Q80 block"):
        validate_kv_quant(bad, 2, "q8")          # ...tp=2 straddles
    with pytest.raises(ValueError, match=r"\b32\b"):
        init_cache_paged_q8(
            TransformerSpec(dim=48, hidden_dim=96, n_layers=1, n_heads=4,
                            n_kv_heads=1, vocab_size=32, seq_len=8), 4, 4)


def test_q8_fallback_warning_fires_once(params, monkeypatch, capsys):
    """--kv-quant q8 with a layout the paged kernel cannot take under an
    ACTIVE pallas mode warns loudly on stderr, once per process (mirrors
    the prefill-flash degrade warning); the default CPU 'xla' mode stays
    silent."""
    from distributed_llama_tpu.runtime import continuous as cont

    # head_size 32 is sub-lane: the kernel never applies to SPEC
    monkeypatch.setattr(cont, "_q8_fallback_warned", False)
    monkeypatch.delenv("DLLAMA_ATTN_KERNEL", raising=False)
    _run(params, steps=2, reqs=[[1, 3]])
    assert "kv-quant" not in capsys.readouterr().err  # xla mode: silent
    monkeypatch.setenv("DLLAMA_ATTN_KERNEL", "pallas")
    _run(params, steps=2, reqs=[[1, 3]])
    err = capsys.readouterr().err
    assert "--kv-quant q8" in err and "XLA gather fallback" in err
    _run(params, steps=2, reqs=[[1, 3]])
    assert "--kv-quant q8" not in capsys.readouterr().err  # once only


def test_q8_metrics_and_pool_gauges(params):
    """The serving surfaces: dllama_kv_quant_info{kv_quant="q8"} = 1 and
    the page-pool byte gauges match the actual device planes."""
    from distributed_llama_tpu.obs.metrics import Registry

    reg = Registry()
    eng, _, _ = _run(params, metrics=reg)
    text = reg.expose()
    assert 'dllama_kv_quant_info{kv_quant="q8"} 1' in text
    pool = reg.get("dllama_kv_page_pool_bytes")
    assert pool is not None
    assert pool.value == sum(int(a.nbytes) for a in eng.cache)
    assert reg.get("dllama_kv_page_bytes").value > 0


def test_q8_cache_halves_page_bytes(params):
    """The capacity claim, measured on the actual device buffers: the q8
    pool's bytes are under half the f32 pool's at the same page count
    (exactly (1 + 2/32) / 4 ≈ 0.266x)."""
    from distributed_llama_tpu.models.llama import (init_cache_paged,
                                                    init_cache_paged_q8)

    f32 = init_cache_paged(SPEC, 9, 4)
    q8 = init_cache_paged_q8(SPEC, 9, 4)
    b_f32 = sum(int(a.nbytes) for a in f32)
    b_q8 = sum(int(a.nbytes) for a in q8)
    assert b_q8 * 2 < b_f32
    kv_dim = SPEC.n_kv_heads * SPEC.head_size
    assert b_q8 == b_f32 // 4 // kv_dim * (kv_dim + 2 * (kv_dim // 32))


# ---------------------------------------------------------------- pricing


def test_memory_model_q8_pricing_and_equal_hbm_pages():
    from distributed_llama_tpu.analysis.memory_model import (
        equal_hbm_kv_pages, kv_page_pool_bytes, kv_position_bytes)
    from distributed_llama_tpu.models.synth import llama2_7b_spec

    spec = llama2_7b_spec()
    kv_dim = spec.n_kv_heads * spec.head_size
    per_f32 = kv_position_bytes(spec, 1)
    per_q8 = kv_position_bytes(spec, 1, kv_quant="q8")
    assert per_f32 == 2 * spec.n_layers * kv_dim * 4
    assert per_q8 == 2 * spec.n_layers * (kv_dim + 2 * (kv_dim // 32))
    # pool formula = pages x page_size x position bytes (+ scrap)
    assert (kv_page_pool_bytes(spec, 1, 100, 16, include_scrap=False,
                               kv_quant="q8")
            == 100 * 16 * per_q8)
    # the equal-HBM multiplier: 32*4/34 = 3.76x, comfortably over the 2x
    # acceptance floor
    pages = equal_hbm_kv_pages(spec, 1, 1000, 16)
    assert 2 * 1000 <= pages == (1000 * 16 * per_f32) // (16 * per_q8)
    with pytest.raises(ValueError):
        kv_position_bytes(spec, 1, kv_quant="int4")


def test_device_footprint_q8_term():
    from distributed_llama_tpu.analysis.memory_model import device_footprint
    from distributed_llama_tpu.models.synth import llama2_7b_spec

    spec = llama2_7b_spec()
    f32 = device_footprint(spec, 1, "fused", kv_page_size=16)
    q8 = device_footprint(spec, 1, "fused", kv_page_size=16,
                          kv_quant="q8")
    assert q8.kv_cache_bytes * 2 < f32.kv_cache_bytes
    assert q8.weights_bytes == f32.weights_bytes
    with pytest.raises(ValueError, match="kv_page_size"):
        device_footprint(spec, 1, "fused", kv_quant="q8")


def test_shardcheck_q8_column_clean_and_catches_stale_verdict():
    """The support matrix's KV-quant column: the declared q8 rows verify
    clean, and a stale q8 verdict (declared not-to-fit but fits) fails
    with the HBM-BUDGET finding — exactly the PR 4 stale-matrix
    contract. An unknown kv_quant value is refused as KV-QUANT."""
    import jax

    from distributed_llama_tpu.analysis.shardcheck import (MatrixEntry,
                                                           check_config)

    if len(jax.devices()) < 8:
        pytest.skip("needs an 8-device virtual mesh (tests/conftest.py "
                    "forces it unless XLA_FLAGS overrides)")
    ok = check_config(MatrixEntry("7b", 8, "fused", "q40", True,
                                  kv_quant="q8"))
    assert ok.ok, [f.render() for f in ok.findings]
    stale = check_config(MatrixEntry("7b", 8, "fused", "q40", False,
                                     kv_quant="q8"))
    assert any(f.rule == "HBM-BUDGET" for f in stale.findings)
    unknown = check_config(MatrixEntry("7b", 8, "fused", "q40", True,
                                       kv_quant="int4"))
    assert any(f.rule == "KV-QUANT" for f in unknown.findings)


def test_journal_fingerprint_refuses_kv_quant_change(params, tmp_path):
    """The recovery guard (satellite 1): a journal with LIVE work written
    under f32 KV must refuse recovery under q8 serving (and vice versa)
    with the drifted key named — a q8 replay of f32-journaled coins
    would be deterministic-but-wrong. Pre-PR-11 journals (no kv_quant
    key) keep recovering under f32."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)
    from distributed_llama_tpu.runtime.journal import (
        JournalConfigMismatch, RequestJournal, config_fingerprint)

    def fp(kv_quant):
        return config_fingerprint(SPEC, "single", "explicit:11",
                                  weights_digest="abcd", kv_quant=kv_quant)

    assert "kv_quant" not in fp("f32")   # pre-PR-11 journals stay valid
    assert fp("q8")["kv_quant"] == "q8"
    # the cache-dtype sibling key: a bf16 cache flip refuses too, with
    # the same omitted-at-f32 legacy compatibility
    bf16 = config_fingerprint(SPEC, "single", "explicit:11",
                              weights_digest="abcd",
                              kv_cache_dtype="bf16")
    assert bf16["kv_cache_dtype"] == "bf16"
    assert "kv_cache_dtype" not in fp("f32")

    path = str(tmp_path / "j")
    j = RequestJournal(path, config=fp("f32"))
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=3, page_size=4, journal=j)
    eng.submit(Request(tokens=[1, 5, 9], steps=8))
    eng.step_many(1, quiet=True)         # live work in the journal

    j2 = RequestJournal(path, config=fp("q8"))
    eng2 = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                            topp=0.9, seed=3, page_size=4, kv_quant="q8",
                            journal=j2)
    with pytest.raises(JournalConfigMismatch, match="kv_quant"):
        eng2.recover()
    # same config recovers fine
    j3 = RequestJournal(path, config=fp("f32"))
    eng3 = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                            topp=0.9, seed=3, page_size=4, journal=j3)
    assert eng3.recover() == 1
    while eng3.step_many(1, quiet=True):
        pass
