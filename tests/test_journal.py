"""Write-ahead request journal (runtime/journal.py, ISSUE 9): append/load
round-trips, the torn-tail repair vs fail-loud corruption contract, fsync
policies, and crash-safe compaction."""

import json
import os

import pytest

from distributed_llama_tpu.runtime.journal import (FSYNC_POLICIES,
                                                   JournalCorruption,
                                                   RequestJournal,
                                                   load_journal)


def _path(tmp_path):
    return str(tmp_path / "requests.journal")


def _fill(j):
    """One retired request, one mid-decode, one queued (admit only)."""
    j.admit(0, [1, 5, 9], steps=8, temperature=0.0, topp=0.9, seed=100)
    j.admit(1, [1, 7], steps=8, temperature=0.9, topp=0.9, seed=101)
    j.admit(2, [1, 2, 3], steps=4, temperature=0.0, topp=0.9, seed=102)
    j.token(0, 42, cursor=0)
    j.token(1, 17, cursor=1)
    j.token(1, 33, cursor=2)
    j.retire(0, "done")
    j.sync(force=True)


def test_round_trip_and_incomplete_set(tmp_path):
    j = RequestJournal(_path(tmp_path))
    _fill(j)
    j.close()
    entries = {e.rid: e for e in load_journal(_path(tmp_path))}
    assert entries[0].status == "done"
    assert entries[1].status is None
    assert entries[1].sampled == [17, 33] and entries[1].cursor == 2
    assert entries[1].replay_tokens == [1, 7, 17, 33]
    assert entries[2].sampled == [] and entries[2].status is None
    # reopening exposes exactly the incomplete set, rid-ordered
    j2 = RequestJournal(_path(tmp_path))
    assert [e.rid for e in j2.incomplete()] == [1, 2]
    # a fresh engine must number past every journaled request
    assert j2.next_id == 3
    j2.close()


def test_torn_tail_truncated_and_reported(tmp_path):
    p = _path(tmp_path)
    j = RequestJournal(p)
    _fill(j)
    j.close()
    good = os.path.getsize(p)
    with open(p, "ab") as fh:
        fh.write(b'{"t":"tok","id":1,"to')  # crash mid-append
    j2 = RequestJournal(p)  # repairs: physically truncates the tail
    assert os.path.getsize(p) == good
    assert [e.rid for e in j2.incomplete()] == [1, 2]
    j2.close()


def test_torn_tail_with_newline_truncated(tmp_path):
    """A torn record whose garbage happens to include the terminating
    newline is still tail damage — truncate, don't refuse."""
    p = _path(tmp_path)
    j = RequestJournal(p)
    _fill(j)
    j.close()
    good = os.path.getsize(p)
    with open(p, "ab") as fh:
        fh.write(b'{"t":"tok","id\n')
    j2 = RequestJournal(p)
    assert os.path.getsize(p) == good
    j2.close()


def test_mid_file_corruption_fails_loudly(tmp_path):
    p = _path(tmp_path)
    j = RequestJournal(p)
    _fill(j)
    j.close()
    with open(p, "r+b") as fh:
        fh.seek(30)  # inside the first admit record, records follow
        fh.write(b"\xff")
    with pytest.raises(JournalCorruption):
        RequestJournal(p)
    with pytest.raises(JournalCorruption):
        load_journal(p)


@pytest.mark.parametrize("damage", [
    b'{"t":"zap","id":0}\n',                       # unknown record type
    b'{"t":"tok","id":99,"tok":1,"cursor":0}\n',   # unadmitted id
    b'{"t":"retire","id":99,"status":"done"}\n',   # retire unadmitted
    b'{"t":"retire","id":1,"status":"maybe"}\n',   # unknown status
    b'{"t":"admit","id":1,"tokens":[],"steps":1,"temperature":0,'
    b'"topp":0.9,"seed":1,"slo":null,"cursor":0}\n',  # duplicate + empty
])
def test_schema_violations_fail_loudly(tmp_path, damage):
    p = _path(tmp_path)
    j = RequestJournal(p)
    _fill(j)
    j.close()
    with open(p, "ab") as fh:
        fh.write(damage)
        fh.write(b'{"t":"retire","id":2,"status":"done"}\n')  # not a tail
    with pytest.raises(JournalCorruption):
        load_journal(p)


def test_missing_header_fails_loudly(tmp_path):
    p = _path(tmp_path)
    with open(p, "wb") as fh:
        fh.write(b'{"t":"admit","id":0,"tokens":[1],"steps":1,'
                 b'"temperature":0,"topp":0.9,"seed":1,"slo":null,'
                 b'"cursor":0}\n')
    with pytest.raises(JournalCorruption):
        load_journal(p)


def test_fully_torn_file_starts_fresh(tmp_path):
    """Killed mid-header-write: no complete line at all — truncate to
    zero and start fresh rather than refusing an empty history."""
    p = _path(tmp_path)
    with open(p, "wb") as fh:
        fh.write(b'{"t":"jour')
    j = RequestJournal(p)
    assert j.incomplete() == []
    j.admit(0, [1, 2], steps=4, temperature=0.0, topp=0.9, seed=5)
    j.close()
    assert [e.rid for e in load_journal(p)] == [0]


def test_compaction_drops_retired_merges_live(tmp_path):
    p = _path(tmp_path)
    j = RequestJournal(p, compact_every=2)
    _fill(j)
    j.retire(2, "cancelled")
    assert j.maybe_compact() == 2  # 2 retired >= compact_every
    j.close()
    entries = load_journal(p)
    # only the live request survives, as ONE merged admit record carrying
    # prompt + sampled-so-far and the coin cursor
    assert [e.rid for e in entries] == [1]
    e = entries[0]
    assert e.tokens == [1, 7, 17, 33] and e.sampled == []
    assert e.cursor == 2 and e.seed == 101
    with open(p, "rb") as fh:
        lines = fh.read().splitlines()
    assert len(lines) == 2  # header + one merged admit
    assert not os.path.exists(p + ".compact")


def test_compaction_then_append_then_reload(tmp_path):
    p = _path(tmp_path)
    j = RequestJournal(p, compact_every=1)
    _fill(j)
    j.compact()
    j.token(1, 55, cursor=3)  # appends continue on the compacted file
    j.retire(1, "done")
    j.close()
    entries = {e.rid: e for e in load_journal(p)}
    assert entries[1].status == "done"
    assert entries[1].sampled == [55]


def test_admit_recovers_atomically_closes_previous_life(tmp_path):
    """A recovery re-admission is ONE record: the new admit's
    ``recovers`` field retires the old life — a crash can never land
    between an open and a close and leave two live entries."""
    path = _path(tmp_path)
    j = RequestJournal(path)
    j.admit(0, [1, 5], steps=4, temperature=0.0, topp=0.9, seed=7)
    j.token(0, 9, cursor=0)
    before = j.records_total
    j.admit(1, [1, 5, 9], steps=4, temperature=0.0, topp=0.9, seed=7,
            recovers=0)
    assert j.records_total == before + 1  # no separate retire append
    j.close()
    entries = {e.rid: e for e in load_journal(path)}
    assert entries[0].status == "recovered"
    assert entries[1].status is None
    j2 = RequestJournal(path)  # append-side reload agrees
    assert [e.rid for e in j2.incomplete()] == [1]
    j2.close()


def test_retire_is_idempotent_and_unknown_safe(tmp_path):
    j = RequestJournal(_path(tmp_path))
    j.admit(0, [1, 2], steps=4, temperature=0.0, topp=0.9, seed=5)
    j.retire(0, "done")
    before = j.records_total
    j.retire(0, "failed")   # already retired: no second record
    j.retire(99, "done")    # never journaled: no record
    assert j.records_total == before
    j.close()


def test_fsync_policies(tmp_path):
    with pytest.raises(ValueError):
        RequestJournal(_path(tmp_path), fsync="sometimes")
    for policy in FSYNC_POLICIES:
        p = str(tmp_path / f"j-{policy}.journal")
        j = RequestJournal(p, fsync=policy)
        j.admit(0, [1, 2], steps=4, temperature=0.0, topp=0.9, seed=5)
        j.sync()
        j.close()
        assert [e.rid for e in load_journal(p)] == [0]


def test_sidecar_metrics_binding(tmp_path):
    from distributed_llama_tpu.obs.metrics import Registry

    reg = Registry()
    c = reg.counter("dllama_journal_records_total", "test")
    j = RequestJournal(_path(tmp_path))
    j.bind_metrics(c)
    j.admit(0, [1, 2], steps=4, temperature=0.0, topp=0.9, seed=5)
    j.token(0, 9, cursor=0)
    j.retire(0, "done")
    j.close()
    assert c.value == 3


def test_wrong_slo_and_cursor_survive_round_trip(tmp_path):
    p = _path(tmp_path)
    j = RequestJournal(p)
    j.admit(0, [1, 2], steps=4, temperature=0.7, topp=0.8, seed=5,
            slo="interactive", cursor=7)
    j.close()
    e = load_journal(p)[0]
    assert e.slo == "interactive" and e.cursor == 7
    assert e.temperature == 0.7 and e.topp == 0.8


def test_header_line_is_versioned(tmp_path):
    p = _path(tmp_path)
    RequestJournal(p).close()
    with open(p) as fh:
        assert json.loads(fh.readline()) == {"t": "journal", "v": 1}
