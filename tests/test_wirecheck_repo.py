"""Tier-1 repo gates (ISSUE 19): the static wirecheck head over the
real runtime/+obs/+tools/ surface must report ZERO findings beyond the
(empty) baseline, the golden wire corpus must regenerate byte-exactly
and pass the version-skew matrix, and the legacy-era (v1) journal
fixture must recover through a real ContinuousEngine — the N−1
compatibility contract, end to end."""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
from distributed_llama_tpu.analysis import wiremodel as wm
from distributed_llama_tpu.analysis.__main__ import (
    DEFAULT_WIRE_BASELINE, PACKAGE_DIR, REPO_ROOT)
from distributed_llama_tpu.analysis.lint import (apply_baseline,
                                                 load_baseline)
from distributed_llama_tpu.analysis.wirecheck import (run_wirecheck,
                                                      wire_files,
                                                      wire_scope)
from distributed_llama_tpu.obs.fleet import (HEALTH_BLOCKS,
                                             ReplicaSignals, rollup,
                                             signals_from_health)

CORPUS = REPO_ROOT / "tests" / "fixtures" / "wire"

_ENV = {"PATH": "/usr/bin:/bin", "HOME": "/tmp",
        "PYTHONPATH": str(REPO_ROOT), "JAX_PLATFORMS": "cpu"}


def _cli(*argv, timeout=600):
    return subprocess.run([sys.executable, *argv], cwd=REPO_ROOT,
                          capture_output=True, text=True,
                          timeout=timeout, env=_ENV)


# -- the static head over the real tree ------------------------------------


def test_package_has_no_new_wirecheck_findings():
    findings = run_wirecheck(wire_files(PACKAGE_DIR, REPO_ROOT),
                             REPO_ROOT)
    baseline = load_baseline(DEFAULT_WIRE_BASELINE)
    new, _, stale = apply_baseline(findings, baseline)
    assert not new, "new wirecheck findings (register the field, fix " \
        "the site, or pragma with a reason):\n" \
        + "\n".join(f.render() for f in new)
    assert not stale, "stale wirecheck baseline entries:\n" \
        + "\n".join(stale)


def test_baseline_is_empty_per_the_burn_down_contract():
    # tools/wirecheck_baseline.txt documents WHY it is empty; this pin
    # keeps it that way — grandfathering wire drift is a deliberate
    # decision that must show up in a diff of this test
    assert not load_baseline(DEFAULT_WIRE_BASELINE), \
        "wirecheck baseline grew an entry: fix or pragma at the site"


def test_scope_covers_runtime_obs_and_tools():
    scoped = [p for p in wire_files(PACKAGE_DIR, REPO_ROOT)
              if wire_scope(p.as_posix())]
    names = {p.as_posix() for p in scoped}
    assert any(n.endswith("runtime/journal.py") for n in names)
    assert any(n.endswith("obs/fleet.py") for n in names)
    assert any(n.endswith("tools/wirecheck.py") for n in names)
    assert any(n.endswith("tools/make_wire_corpus.py") for n in names)
    assert not any("/models/" in n for n in names)
    assert len(scoped) >= 30  # the whole cross-process surface


def test_cli_wirecheck_exits_zero_on_repo():
    proc = _cli("-m", "distributed_llama_tpu.analysis", "--wirecheck")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "wirecheck: 0 new finding(s)" in proc.stdout


def test_wirecheck_only_invocation_skips_the_lint_head(capsys):
    from distributed_llama_tpu.analysis.__main__ import main

    rc = main(["--wirecheck"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "wirecheck:" in out
    assert "dlint:" not in out


def test_write_wirecheck_baseline_refuses_partial_scans(tmp_path):
    from distributed_llama_tpu.analysis.__main__ import main

    target = PACKAGE_DIR / "runtime" / "journal.py"
    rc = main(["--wirecheck", "--write-wirecheck-baseline",
               "--wirecheck-baseline", str(tmp_path / "wb.txt"),
               str(target)])
    assert rc == 2
    assert not (tmp_path / "wb.txt").exists()


# -- the health schema stamp (satellite: /health versioning) ---------------


def test_health_schema_constant_matches_the_registry():
    from distributed_llama_tpu.runtime.server import HEALTH_SCHEMA

    assert HEALTH_SCHEMA == wm.HEALTH_SCHEMA_VERSION
    assert wm.FORMATS_BY_NAME["health"].version == HEALTH_SCHEMA


# -- the golden corpus + skew matrix ---------------------------------------


def test_corpus_regenerates_byte_identically(tmp_path):
    proc = _cli("tools/make_wire_corpus.py", "--out",
                str(tmp_path / "wire"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    fresh = {p.relative_to(tmp_path / "wire").as_posix(): p
             for p in (tmp_path / "wire").rglob("*") if p.is_file()}
    checked_in = {p.relative_to(CORPUS).as_posix(): p
                  for p in CORPUS.rglob("*") if p.is_file()}
    assert set(fresh) == set(checked_in), \
        "corpus file set drifted — regenerate and commit"
    for rel, path in sorted(fresh.items()):
        assert path.read_bytes() == checked_in[rel].read_bytes(), \
            f"corpus file {rel} is not byte-deterministic (or the " \
            f"checked-in copy is stale): rerun tools/make_wire_corpus.py"


def test_skew_matrix_passes_and_stamps_its_row():
    proc = _cli("tools/wirecheck.py", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    row = json.loads(proc.stdout)
    assert row["tool"] == "wirecheck" and row["ok"]
    assert row["failed"] == 0 and row["checks"] >= 20
    assert {"tp_scheme", "env_fingerprint"} <= set(row["stamp"])
    eras = {(r["format"], r["era"]) for r in row["matrix"]}
    # every versioned format proves BOTH eras readable
    for fmt in ("journal", "handoff", "health", "metrics", "bundle",
                "fingerprint"):
        assert (fmt, "v1") in eras and (fmt, "v2") in eras


def test_skew_reader_injection_exits_exactly_one():
    proc = _cli("tools/wirecheck.py", "--inject", "skew-reader")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "FAIL" in proc.stdout


def test_drop_registry_field_injection_exits_exactly_one():
    proc = _cli("tools/wirecheck.py", "--inject", "drop-registry-field")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "W001" in proc.stdout


# -- the N−1 journal recovers through a REAL engine ------------------------


def test_legacy_journal_recovers_through_the_engine(tmp_path):
    """Satellite 2: the v1 corpus WAL (no trace, no ledger, one admit
    without slo/cursor keys at all) must re-admit through
    ContinuousEngine.recover and drain to completion — the version-skew
    contract at the engine level, not just the parser level."""
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.obs.metrics import Registry
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine
    from distributed_llama_tpu.runtime.journal import (RequestJournal,
                                                       load_journal)

    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=2,
                           n_heads=4, n_kv_heads=2, vocab_size=128,
                           seq_len=32)
    params = synth_params(spec, q40=False, seed=4, scale=0.3)
    wal = tmp_path / "journal.wal"
    shutil.copy(CORPUS / "journal" / "v1" / "journal.wal", wal)

    eng = ContinuousEngine(
        spec, params, journal=RequestJournal(str(wal)), slots=2,
        temperature=0.8, topp=0.9, seed=11, metrics=Registry(),
        prefill_chunk=4, page_size=4, kv_pages=24)
    assert eng.recover() == 2  # rids 1 and 2 were live

    entries = {e.rid: e for e in load_journal(str(wal))}
    assert entries[1].status == "recovered"
    assert entries[2].status == "recovered"
    live = sorted(e.rid for e in entries.values() if e.status is None)
    assert len(live) == 2
    # rid 1's successor replays prompt + both sampled tokens from
    # coin-cursor 2; rid 2's successor replays the bare prompt
    replays = sorted(entries[r].tokens for r in live)
    assert replays == [[1, 5, 9, 17, 23], [2, 4]]
    assert sorted(entries[r].cursor for r in live) == [0, 2]

    while eng.step_many(eng.block_steps, quiet=True):
        pass
    entries = {e.rid: e for e in load_journal(str(wal))}
    assert all(e.status is not None for e in entries.values())


# -- fleet presence semantics (satellite: absent-cell rollups) -------------


def _corpus_row(name: str, era: str) -> ReplicaSignals:
    payload = json.loads((CORPUS / "health" / era
                          / "health.json").read_text())
    return signals_from_health(name, payload)


def test_rollup_skips_absent_blocks_instead_of_zero_filling():
    old = _corpus_row("old", "v1")   # schema 0: paged_kv + slo only
    new = _corpus_row("new", "v2")   # schema 3: every block
    agg = rollup([old, new])
    assert (agg.schema_min, agg.schema_max) == (0, 3)
    # both replicas report the kv + slo planes; only the new build
    # reports the cost plane — the rollup must say so, not dilute
    assert agg.reporting["paged_kv"] == 2
    assert agg.reporting["slo"] == 2
    assert agg.reporting["sched"] == 1
    assert agg.goodput_tokens == 40 + 70
    assert agg.page_seconds == 0.25     # old replica: absent, not 0.0
    assert agg.stall_seconds == {"page_wait": 0.125}
    assert agg.kv_pages == 48 and agg.kv_pages_free == 34


def test_directly_built_rows_keep_counting_everywhere():
    # present=None (a row built in code, not parsed from /health) means
    # presence is unknown: every block counts, the pre-ISSUE-19 behavior
    row = ReplicaSignals(name="direct", healthy=True, state="serving",
                         goodput_tokens=5, page_seconds=0.5)
    assert row.present is None
    assert all(row.reports(b) for b in HEALTH_BLOCKS)
    agg = rollup([row, _corpus_row("old", "v1")])
    assert agg.goodput_tokens == 45
    assert agg.page_seconds == 0.5
    assert agg.reporting["sched"] == 1  # the direct row only


def test_present_set_serializes_into_the_fleet_row_json():
    row = _corpus_row("old", "v1")
    out = row.to_json()
    assert out["present"] == ["paged_kv", "slo"]
    assert out["schema"] == 0
