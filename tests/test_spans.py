"""Span tracer + named-scope threading: the tentpole's emission side.

Covers obs/spans.py (nesting, thread safety, Chrome/NDJSON exports, the
schema validator), the engine's span wiring (/debug/timeline round trip,
dark-engine silence), and the contract that parallel/tp.py's traced
forward actually CARRIES the canonical phase/collective scope names the
xprof loader buckets by."""

import json
import threading
import time
import urllib.request

import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.obs.spans import (COLLECTIVE_SCOPE_KINDS,
                                             PHASE_SCOPES, SpanTracer,
                                             validate_chrome_trace)

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


class _IdTokenizer:
    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"<%d>" % tok


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


# ------------------------------------------------------------- tracer


def test_span_nesting_depth_and_meta():
    tr = SpanTracer()
    with tr.span("request", cat="request", index=0):
        with tr.span("step", cat="decode", active=2):
            time.sleep(0.001)
    spans = tr.snapshot()
    # inner completes first; depths rebuild the hierarchy
    assert [(s.name, s.depth) for s in spans] == [("step", 1),
                                                  ("request", 0)]
    assert spans[0].meta == {"active": 2}
    assert spans[0].dur_s > 0
    assert spans[1].dur_s >= spans[0].dur_s


def test_span_records_on_exception():
    tr = SpanTracer()
    with pytest.raises(RuntimeError):
        with tr.span("step", cat="decode"):
            raise RuntimeError("boom")
    (s,) = tr.snapshot()
    assert s.meta["error"].startswith("RuntimeError")
    # the stack unwound: a new span starts back at depth 0
    with tr.span("next"):
        pass
    assert tr.snapshot()[-1].depth == 0


def test_span_ring_buffer_bounds_memory():
    tr = SpanTracer(capacity=8)
    for i in range(50):
        tr.add(f"s{i}", "phase", float(i), 0.001)
    spans = tr.snapshot()
    assert len(spans) == 8
    assert spans[0].name == "s42" and spans[-1].name == "s49"


def test_span_tracer_thread_safety():
    tr = SpanTracer(capacity=10000)

    def worker(k):
        for _ in range(100):
            with tr.span(f"w{k}"):
                pass

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = tr.snapshot()
    assert len(spans) == 800
    assert all(s.depth == 0 for s in spans)  # per-thread stacks don't mix


def test_chrome_export_is_valid_and_ordered():
    tr = SpanTracer()
    with tr.span("step", cat="decode", active=1):
        pass
    doc = tr.export_chrome()
    validate_chrome_trace(doc)  # the schema gate used on CI artifacts
    (ev,) = doc["traceEvents"]
    assert ev["ph"] == "X" and ev["cat"] == "decode"
    assert ev["args"]["active"] == 1 and ev["args"]["depth"] == 0


def test_ndjson_export_one_object_per_line():
    tr = SpanTracer()
    with tr.span("prefill", cat="prefill", tokens=7):
        pass
    lines = tr.export_ndjson().strip().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert recs[0]["span"] == "prefill" and recs[0]["tokens"] == 7
    assert tr.export_ndjson().endswith("\n")
    assert SpanTracer().export_ndjson() == ""


def test_validate_chrome_trace_rejects_malformed():
    with pytest.raises(ValueError):
        validate_chrome_trace({"events": []})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [{"name": "x", "ph": "Z",
                                                "ts": 0}]})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [{"name": "x", "ph": "X",
                                                "ts": -1, "dur": 1}]})
    with pytest.raises(ValueError):
        validate_chrome_trace({"traceEvents": [{"name": "x", "ph": "X",
                                                "ts": 0}]})  # no dur
    validate_chrome_trace({"traceEvents": []})  # empty is fine


# ------------------------------------------- named scopes in the forward


def _name_stacks(jaxpr, out=None):
    """Every eqn's name-stack string, recursing into sub-jaxprs (scan
    bodies, shard_map callees)."""
    import jax

    out = [] if out is None else out
    for eqn in jaxpr.eqns:
        st = getattr(eqn.source_info, "name_stack", None)
        if st is not None:
            out.append(str(st))
        for v in eqn.params.values():
            leaves = v if isinstance(v, (list, tuple)) else [v]
            for leaf in leaves:
                if isinstance(leaf, jax.core.ClosedJaxpr):
                    _name_stacks(leaf.jaxpr, out)
                elif hasattr(leaf, "eqns"):  # raw Jaxpr
                    _name_stacks(leaf, out)
    return out


@pytest.mark.parametrize("scheme", ["ref", "fused", "overlap"])
def test_tp_forward_carries_phase_and_collective_scopes(scheme):
    """The traced tp forward must label every phase and every collective
    at source — the attribution contract obs/xprof.py buckets by."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import make_mesh, shard_params
    from distributed_llama_tpu.parallel.tp import make_sharded_forward

    mesh = make_mesh(tp=2)
    params = shard_params(synth_params(SPEC, q40=False, seed=0), mesh,
                          scheme=scheme)
    cache = init_cache(SPEC)
    fwd = make_sharded_forward(SPEC, mesh, scheme=scheme)
    jaxpr = jax.make_jaxpr(lambda p, c, t, s: fwd(p, c, t, s))(
        params, cache, jnp.zeros((1,), jnp.int32), jnp.int32(0))
    stacks = _name_stacks(jaxpr.jaxpr)
    if not stacks:
        pytest.skip("this jax exposes no eqn name stacks")
    blob = "\n".join(stacks)
    for scope in PHASE_SCOPES:
        assert scope in blob, f"phase scope {scope!r} missing from trace"
    expected_coll = {"ref": ["ici_all_gather"],
                     "fused": ["ici_all_gather", "ici_psum"],
                     "overlap": ["ici_all_gather", "ici_ppermute"]}[scheme]
    for scope in expected_coll:
        assert scope in blob, f"collective scope {scope!r} missing"


# --------------------------------------------- engine + /debug/timeline


def test_engine_records_spans_when_enabled(params):
    from distributed_llama_tpu.obs.metrics import Registry
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=Registry())
    eng.run([[1, 5, 9], [1, 7]], steps=8)
    names = {s.name for s in eng._spans.snapshot()}
    assert "step" in names or "chain" in names
    assert "request" in names
    reqs = [s for s in eng._spans.snapshot() if s.name == "request"]
    assert len(reqs) == 2
    assert all(s.meta["tokens"] > 0 for s in reqs)


def test_engine_chain_spans_and_prefill(params):
    from distributed_llama_tpu.obs.metrics import Registry
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    eng = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                           topp=0.9, seed=5, block_steps=3,
                           prefill_chunk=2, metrics=Registry())
    eng.run([[1, 5, 9, 2, 8]], steps=10)
    names = [s.name for s in eng._spans.snapshot()]
    assert "chain" in names
    assert "prefill" in names


def test_engine_dark_records_no_spans(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    eng = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                           topp=0.9, seed=5)
    eng.run([[1, 5]], steps=4)
    assert eng._spans is None


def test_server_debug_timeline_endpoint(params):
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=6, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)
    srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate",
            data=json.dumps({"prompt": "ab", "steps": 6}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert json.loads(r.read())["steps"] > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/timeline",
                timeout=30) as r:
            assert r.headers["Content-Type"] == "application/json"
            doc = json.loads(r.read())
        validate_chrome_trace(doc)
        names = {ev["name"] for ev in doc["traceEvents"]}
        assert "request" in names and ("step" in names or "chain" in names)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/timeline?format=ndjson",
                timeout=30) as r:
            assert r.headers["Content-Type"] == "application/x-ndjson"
            lines = r.read().decode().strip().splitlines()
        assert any(json.loads(ln)["span"] == "request" for ln in lines)
    finally:
        srv.stop()


def test_server_timeline_404_when_disabled(params):
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=1, steps=4, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, metrics=False)
    srv.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/timeline", timeout=30)
        assert ei.value.code == 404
    finally:
        srv.stop()


def test_collective_scope_kinds_are_budget_kinds():
    """The scope→kind map must speak the budget's vocabulary — a rename
    on either side silently unjoins measurement from model."""
    from distributed_llama_tpu.models.synth import llama2_7b_spec
    from distributed_llama_tpu.parallel.comm_stats import (
        SCHEMES, tp_collective_budget)

    budget_kinds = set()
    for scheme in SCHEMES:
        budget_kinds |= set(
            tp_collective_budget(llama2_7b_spec(), 8, scheme).kind_counts())
    assert budget_kinds <= set(COLLECTIVE_SCOPE_KINDS.values())
