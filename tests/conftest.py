"""Test env: force pure-CPU jax with an 8-device virtual mesh.

Multi-chip sharding tests run on 8 virtual CPU devices (the TPU pod stand-in);
real-TPU runs go through bench.py / the CLI, which do not import this.

The axon sitecustomize (TPU tunnel) sets jax_platforms='axon,cpu' as explicit
config at interpreter start, which both overrides JAX_PLATFORMS=cpu and makes
every jax.devices() call try to dial the tunnel — so we must re-update the
config value, not just the env var, before any backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu"

# NOTE: do NOT enable jax's persistent compilation cache here — measured
# on this suite it makes the cross-program bitwise pins
# (test_checkpoint's split-generation tests) fail NONDETERMINISTICALLY:
# a deserialized cached executable is not always bit-identical to the
# fresh in-process compile of the same HLO. In-process program reuse is
# handled deterministically by runtime.continuous._shared_program
# (engines with equal (spec, mesh, scheme, ...) share the SAME jitted
# callable, so identical programs compile once per process).

import pytest  # noqa: E402

# Tests marked slow and deselected from the default run (pytest.ini). One
# tunable place, chosen from measured -n 8 durations: multi-process
# jax.distributed spawns, training soaks, deep-position/randomized parity
# sweeps, and the heaviest sharded-compile combos. Their feature areas all
# keep lighter always-on coverage; tools/ci.sh runs everything.
# DLLAMA_RUN_SLOW=1 also re-includes them without editing flags.
SLOW_FILES = {"test_multihost.py", "test_sp_train.py", "test_train_cli.py"}
SLOW_TESTS = {
    "test_bench_all_emits_one_json_line_with_rows",
    "test_prefill_early_bos_rng_rewind",
    "test_continuous_more_requests_than_slots",
    "test_continuous_randomized_workloads_agree",
    "test_continuous_over_mesh_matches_single_chip",
    "test_forward_batch_ragged_matches_singles",
    "test_train_step_loss_decreases",
    "test_train_checkpoint_exact_resume",
    "test_convert_hf_logit_parity",
    "test_tp_sharded_forward_with_kernel_layout",
    "test_tp_sharded_forward_with_flash_attention",
    "test_pack_q40_params_and_forward_parity",
    "test_deep_position_decode_parity",
    "test_cli_batch_prompts_file",
    "test_sp_decode_parity",
    "test_batch_sp_step_matches_single_chip",
    "test_batch_tp_step_matches_single_chip",
    "test_decode_matches_prefill",
    "test_deep_gqa_continuous_composed",
    "test_forward_batch_matches_singles",
    "test_generate_prefill_on_sharded_engine",
    "test_fast_resume_crosses_loops",
    # recovery drills that spawn a fresh jax subprocess (ISSUE 9)
    "test_kill_mid_decode_drill_recovers_bitwise",
    "test_corrupt_journal_turns_kill_drill_red",
    # the full tp x scheme x kv-quant paged-kernel routing grid (ISSUE 11):
    # 18 sharded-forward traces; the fast suite keeps the single-chip
    # routing cases (test_paged_kernel_routing_single_chip) and ci.sh runs
    # the grid explicitly
    "test_paged_kernel_routing_tp_scheme_grid",
}


def pytest_collection_modifyitems(config, items):
    if os.environ.get("DLLAMA_RUN_SLOW"):
        return
    for item in items:
        base = item.name.split("[")[0]
        if base in SLOW_TESTS or item.path.name in SLOW_FILES:
            item.add_marker(pytest.mark.slow)
