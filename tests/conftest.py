"""Test env: force pure-CPU jax with an 8-device virtual mesh.

Multi-chip sharding tests run on 8 virtual CPU devices (the TPU pod stand-in);
real-TPU runs go through bench.py / the CLI, which do not import this.

The axon sitecustomize (TPU tunnel) sets jax_platforms='axon,cpu' as explicit
config at interpreter start, which both overrides JAX_PLATFORMS=cpu and makes
every jax.devices() call try to dial the tunnel — so we must re-update the
config value, not just the env var, before any backend initializes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu"
