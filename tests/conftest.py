"""Test env: force an 8-device virtual CPU mesh before jax is imported.

Multi-chip sharding tests run on 8 virtual CPU devices (the TPU pod stand-in);
real-TPU runs go through bench.py / the CLI, which do not import this.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
