"""tools/tracejoin.py: stitch, skew-align, refuse on orphans (ISSUE 15).

Unit-level gates on synthetic NDJSON exports (known skew, known orphan
shapes) plus the CLI file mode's exit codes. The full two-pool drill
(real engines + TCP page channel) runs in tools/ci.sh and the slow-
marked continuity suite."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

import tracejoin  # noqa: E402

from distributed_llama_tpu.obs import tracectx  # noqa: E402
from distributed_llama_tpu.obs.spans import validate_chrome_trace  # noqa: E402


def _span(name, cat, t0, dur_ms, ctx=None, **extra):
    rec = {"span": name, "cat": cat, "t_start_s": t0, "dur_ms": dur_ms,
           "tid": 1, "depth": 0}
    if ctx is not None:
        rec.update(tracectx.span_fields(ctx))
    rec.update(extra)
    return rec


def _two_pool_exports(skew_s=5.0):
    """A minimal well-formed pair of exports: decode pool holds the root
    request + handoff send; prefill pool (clock shifted by ``skew_s``)
    holds the recv + the stub's request span; the decode continuation
    links on the stub span."""
    root = tracectx.mint()
    rpc = root.child()
    recv = rpc.child()
    stub = recv.child()
    cont = stub.child(link="handoff")
    decode = [
        _span("request", "request", 1.0, 400.0, root),
        _span("handoff", "handoff", 1.05, 200.0, rpc),
        _span("handoff", "link", 1.3, 0.0, cont),
        _span("request", "request", 1.3, 90.0, cont),
    ]
    prefill = [
        # prefill's clock: its epoch differs by skew_s
        _span("prefill_handoff", "handoff", 1.10 - skew_s, 80.0, recv),
        _span("request", "request", 1.06 - skew_s, 30.0, stub),
    ]
    return decode, prefill, root


def test_join_aligns_skew_and_reports_pair():
    decode, prefill, root = _two_pool_exports(skew_s=5.0)
    doc, report = tracejoin.join_pools(decode, prefill, "decode",
                                       "prefill")
    assert report["orphans"] == []
    assert report["pairs"] == 1
    # recovered offset = the injected skew (midpoint alignment is exact
    # here because the synthetic recv is centered where it was recorded)
    send_mid = 1.05 + 0.1
    recv_mid = (1.10 - 5.0) + 0.04
    assert report["offset_s"] == pytest.approx(send_mid - recv_mid,
                                               abs=1e-6)
    assert root.trace_id in report["traces_joined"]
    validate_chrome_trace(doc)
    # both pools present as distinct pid lanes, recv inside send after
    # the shift
    by_name = {}
    for ev in doc["traceEvents"]:
        if ev["ph"] == "X":
            by_name.setdefault(ev["name"], []).append(ev)
    (send,) = [e for e in by_name["handoff"]
               if e["args"].get("link") is None and e["cat"] == "handoff"]
    (recv,) = by_name["prefill_handoff"]
    assert send["pid"] != recv["pid"]
    assert send["ts"] <= recv["ts"]
    assert recv["ts"] + recv["dur"] <= send["ts"] + send["dur"] + 1e-3


def test_orphan_unmatched_send_and_recv():
    decode, prefill, _ = _two_pool_exports()
    # drop the recv: the send is unmatched
    _, report = tracejoin.join_pools(decode, [prefill[1]], "d", "p")
    assert any("no recv span" in o for o in report["orphans"])
    assert report["pairs"] == 0
    # a recv whose parent never shipped (fresh root) is sender-less
    alien = tracectx.mint()
    prefill2 = [_span("prefill_handoff", "handoff", 0.0, 10.0, alien),
                prefill[1]]
    _, report2 = tracejoin.join_pools([decode[0]], prefill2, "d", "p")
    assert any("no matching send" in o for o in report2["orphans"])


def test_orphan_link_without_parent():
    decode, prefill, _ = _two_pool_exports()
    # strip the stub's request span: the continuation link's parent is
    # gone from the joined set
    _, report = tracejoin.join_pools(decode, [prefill[0]], "d", "p")
    assert any("link span" in o and "absent" in o
               for o in report["orphans"])
    # a 'recovers' link is EXEMPT: its parent span died with the
    # crashed process's tracer — expected-missing, not a break
    ghost = tracectx.mint().child(link="recovers")
    decode2 = decode + [_span("recovers", "link", 2.0, 0.0, ghost)]
    _, report2 = tracejoin.join_pools(decode2, prefill, "d", "p")
    assert report2["orphans"] == []


def test_load_ndjson_consumes_meta_and_rejects_garbage(tmp_path):
    p = tmp_path / "a.ndjson"
    p.write_text(json.dumps({"span": "x", "cat": "phase",
                             "t_start_s": 0.0, "dur_ms": 1.0}) + "\n"
                 + json.dumps({"span": "_meta", "cat": "meta",
                               "dropped": 3}) + "\n")
    spans, dropped = tracejoin.load_ndjson_spans(str(p))
    assert len(spans) == 1 and dropped == 3
    bad = tmp_path / "b.ndjson"
    bad.write_text("not json\n")
    with pytest.raises(ValueError):
        tracejoin.load_ndjson_spans(str(bad))


def test_cli_exit_codes(tmp_path):
    decode, prefill, _ = _two_pool_exports()
    pa, pb = tmp_path / "a.ndjson", tmp_path / "b.ndjson"
    pa.write_text("\n".join(json.dumps(s) for s in decode) + "\n")
    pb.write_text("\n".join(json.dumps(s) for s in prefill) + "\n")
    out = tmp_path / "joined.json"
    assert tracejoin.main([str(pa), str(pb), "--chrome-out", str(out),
                           "--json"]) == 0
    validate_chrome_trace(json.loads(out.read_text()))
    # orphaned input -> exit 1, and no artifact is written
    pb_orphan = tmp_path / "b2.ndjson"
    pb_orphan.write_text(json.dumps(prefill[1]) + "\n")
    out2 = tmp_path / "joined2.json"
    assert tracejoin.main([str(pa), str(pb_orphan), "--chrome-out",
                           str(out2), "--json"]) == 1
    assert not out2.exists()
    # usage errors are 2, never a vacuous 0/1
    assert tracejoin.main([str(pa)]) == 2
    assert tracejoin.main([str(pa), str(pb), "--inject",
                           "drop-traceparent"]) == 2
    assert tracejoin.main([str(pa), "missing.ndjson"]) == 2
