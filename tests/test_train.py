"""Training step: loss decreases on a memorization task, sharded over dp x tp."""

import numpy as np

from distributed_llama_tpu.models.spec import TransformerSpec

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=96, seq_len=16)


def _params(seed=3, scale=0.1):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    p = {"tok_embedding": t(SPEC.vocab_size, SPEC.dim),
         "rms_final": 1 + t(SPEC.dim), "wcls": t(SPEC.vocab_size, SPEC.dim),
         "rms_att": 1 + t(SPEC.n_layers, SPEC.dim),
         "rms_ffn": 1 + t(SPEC.n_layers, SPEC.dim)}
    for name, shape in SPEC.layer_matmul_shapes():
        p[name] = t(SPEC.n_layers, *shape)
    return p


def test_train_step_loss_decreases():
    import jax.numpy as jnp

    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.parallel.train import make_train_step

    mesh = make_mesh(dp=2, tp=4)
    init_fn, step_fn = make_train_step(SPEC, mesh, learning_rate=3e-3)
    params, opt_state = init_fn(_params())

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, SPEC.vocab_size, (4, 9)),
                         dtype=jnp.int32)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step_fn(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_train_checkpoint_exact_resume(tmp_path):
    """save -> load into a FRESH sharded state -> continue: identical to an
    uninterrupted run (params bit-equal, losses equal). Load also restores
    the template's shardings, including across a different mesh shape."""
    import jax.numpy as jnp

    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.parallel.train import (load_train_state,
                                                      make_train_step,
                                                      save_train_state)

    mesh = make_mesh(dp=2, tp=4)
    init_fn, step_fn = make_train_step(SPEC, mesh, learning_rate=3e-3)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, SPEC.vocab_size, (4, 9)),
                         dtype=jnp.int32)

    params, opt = init_fn(_params())
    ref_losses = []
    for _ in range(4):
        params, opt, loss = step_fn(params, opt, tokens)
        ref_losses.append(float(loss))
    ref_params = params

    params, opt = init_fn(_params())
    for _ in range(2):
        params, opt, loss = step_fn(params, opt, tokens)
    ck = str(tmp_path / "train.npz")
    save_train_state(ck, SPEC, params, opt)

    # resume on a DIFFERENT mesh shape: templates carry the new shardings
    mesh2 = make_mesh(dp=1, tp=2)
    init2, step2 = make_train_step(SPEC, mesh2, learning_rate=3e-3)
    p2, o2 = init2(_params())
    p2, o2 = load_train_state(ck, SPEC, p2, o2)

    # straight after load (before GSPMD repicks output shardings): AdamW
    # moments come back band-sharded like their params, not replicated
    # (2x params of HBM per device at real sizes)
    from jax.sharding import PartitionSpec as P
    mu = o2[0].mu
    assert mu["wq"].sharding.spec == P(None, "tp", None), mu["wq"].sharding
    assert mu["rms_att"].sharding.spec == P()

    losses2 = []
    for _ in range(2):
        p2, o2, loss = step2(p2, o2, tokens)
        losses2.append(float(loss))
    np.testing.assert_allclose(losses2, ref_losses[2:], rtol=1e-6, atol=1e-6)
    for k in ref_params:
        np.testing.assert_allclose(np.asarray(p2[k]),
                                   np.asarray(ref_params[k]),
                                   rtol=1e-6, atol=1e-6)

    # guards: wrong spec, wrong structure, wrong dtype are refused
    import pytest

    other = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                            n_kv_heads=SPEC.n_kv_heads,
                            vocab_size=SPEC.vocab_size, seq_len=64)
    with pytest.raises(ValueError, match="header"):
        load_train_state(ck, other, p2, o2)
    with pytest.raises(ValueError, match="leaves"):
        load_train_state(ck, SPEC, {"only": p2["wq"]}, o2)
    import jax.numpy as jnp2
    bad = dict(p2)
    bad["rms_final"] = p2["rms_final"].astype(jnp2.bfloat16)
    with pytest.raises(ValueError, match="dtype"):
        load_train_state(ck, SPEC, bad, o2)


def test_forward_seq_matches_cached_forward():
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, forward_seq,
                                                    init_cache)

    p = {k: jnp.asarray(v) for k, v in _params().items()}
    tokens = np.array([[1, 5, 9, 2, 17]], dtype=np.int32)
    lg_seq = forward_seq(SPEC, p, jnp.asarray(tokens))
    lg_cache, _ = forward(SPEC, p, init_cache(SPEC),
                          jnp.asarray(tokens[0]), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(lg_seq[0]), np.asarray(lg_cache),
                               rtol=0, atol=3e-5)
