"""Training step: loss decreases on a memorization task, sharded over dp x tp."""

import numpy as np

from distributed_llama_tpu.models.spec import TransformerSpec

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=96, seq_len=16)


def _params(seed=3, scale=0.1):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    p = {"tok_embedding": t(SPEC.vocab_size, SPEC.dim),
         "rms_final": 1 + t(SPEC.dim), "wcls": t(SPEC.vocab_size, SPEC.dim),
         "rms_att": 1 + t(SPEC.n_layers, SPEC.dim),
         "rms_ffn": 1 + t(SPEC.n_layers, SPEC.dim)}
    for name, shape in SPEC.layer_matmul_shapes():
        p[name] = t(SPEC.n_layers, *shape)
    return p


def test_train_step_loss_decreases():
    import jax.numpy as jnp

    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.parallel.train import make_train_step

    mesh = make_mesh(dp=2, tp=4)
    init_fn, step_fn = make_train_step(SPEC, mesh, learning_rate=3e-3)
    params, opt_state = init_fn(_params())

    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, SPEC.vocab_size, (4, 9)),
                         dtype=jnp.int32)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step_fn(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_forward_seq_matches_cached_forward():
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, forward_seq,
                                                    init_cache)

    p = {k: jnp.asarray(v) for k, v in _params().items()}
    tokens = np.array([[1, 5, 9, 2, 17]], dtype=np.int32)
    lg_seq = forward_seq(SPEC, p, jnp.asarray(tokens))
    lg_cache, _ = forward(SPEC, p, init_cache(SPEC),
                          jnp.asarray(tokens[0]), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(lg_seq[0]), np.asarray(lg_cache),
                               rtol=0, atol=3e-5)
