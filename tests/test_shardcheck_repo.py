"""Tier-1 repo gate for shardcheck (analysis/shardcheck.py).

Four layers of defense, mirroring the dlint gate's structure:

* the FULL declared support matrix (7B/13B/70B x tp 1-8 x ref/fused x
  Q40/F16) verifies clean — sharding == tp.py's contract, no rogue
  dequants, uniform shards, HBM verdicts match the declaration;
* the closed-form weight+KV footprints match INDEPENDENT hand
  calculations (raw spec dims, no memory_model helpers) to within 1%;
* mutation self-tests: a deliberately replicated weight reports J004, a
  KV-budget overshoot reports the budget failure, a rogue dequant reports
  J005, ragged heads report J006 — the checker itself cannot rot green;
* the dequant-site registry resolves to real functions, so a renamed
  sanctioned site fails here instead of silently allowing nothing.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from distributed_llama_tpu.analysis import shardcheck as sc
from distributed_llama_tpu.analysis.memory_model import (
    GIB, device_footprint, live_interval_peak)
from distributed_llama_tpu.models.synth import small_bench_spec
from distributed_llama_tpu.ops.quants import FloatType


@pytest.fixture(scope="module")
def matrix_results():
    return sc.run_shardcheck()


def test_full_support_matrix_is_clean(matrix_results):
    # 72 f32 configs + the 12-entry q8 KV-quant column (ISSUE 11)
    assert len(matrix_results) == len(sc.SUPPORT_MATRIX) == 84
    bad = [f.render() for r in matrix_results for f in r.findings]
    assert not bad, "\n".join(bad)


def test_matrix_covers_the_declared_grid():
    labels = {e.label for e in sc.SUPPORT_MATRIX}
    for m in ("7b", "13b", "70b"):
        for tp in (1, 2, 4, 8):
            for s in ("ref", "fused", "overlap"):
                for w in ("q40", "f16"):
                    assert f"{m}-tp{tp}-{s}-{w}" in labels
            # the q8 KV-quant column rides the serving codec (q40) under
            # the fused scheme across the whole tp grid
            assert f"{m}-tp{tp}-fused-q40-q8" in labels


# -- closed-form hand calculations (independent arithmetic) -----------------

# (dim, hidden, layers, heads, kv_heads, vocab, seq)
_DIMS = {"7b": (4096, 11008, 32, 32, 32, 32000, 2048),
         "13b": (5120, 13824, 40, 40, 40, 32000, 2048),
         "70b": (8192, 28672, 80, 64, 8, 32000, 2048)}


def _hand_weight_values(model: str) -> int:
    d, h, L, nh, nkv, v, _ = _DIMS[model]
    kv = d * nkv // nh
    per_layer = d * d + kv * d + kv * d + d * d + h * d + d * h + h * d
    return L * per_layer + v * d  # + wcls


@pytest.mark.parametrize("model", ("7b", "13b", "70b"))
@pytest.mark.parametrize("tp", (1, 2, 4, 8))
@pytest.mark.parametrize("wtype", ("q40", "f16"))
def test_weight_and_kv_footprints_match_hand_calc(matrix_results, model,
                                                  tp, wtype):
    d, h, L, nh, nkv, v, seq = _DIMS[model]
    values = _hand_weight_values(model) // tp
    # Q40 kernel layout: 16 B codes + 4 B f32 scale per 32 values
    want_w = values // 32 * 20 if wtype == "q40" else 2 * values
    want_kv = 2 * L * seq * (nkv // tp) * (d // nh) * 4
    label = f"{model}-tp{tp}-fused-{wtype}"
    rep = next(r.report for r in matrix_results if r.config == label)
    assert abs(rep.weights_bytes - want_w) <= 0.01 * want_w
    assert abs(rep.kv_cache_bytes - want_kv) <= 0.01 * want_kv
    # replicated embedding: vocab x dim f32, norms are noise next to it
    assert abs(rep.replicated_bytes - v * d * 4) <= 0.01 * (v * d * 4) \
        + (2 * L + 1) * d * 4


def test_headline_70b_tp8_q40_fits_with_headroom(matrix_results):
    rep = next(r.report for r in matrix_results
               if r.config == "70b-tp8-fused-q40")
    assert rep.fits
    # ~5.0 GiB weights + ~1 GiB embedding + small KV: well under 14.4 GiB
    assert 5.5 * GIB < rep.total_bytes < 8 * GIB
    assert rep.headroom_bytes > 6 * GIB


def test_70b_never_fits_unsharded(matrix_results):
    for r in matrix_results:
        if r.config.startswith("70b-tp1"):
            assert not r.report.fits


# -- mutation self-tests (the checker must catch what it claims to) ---------


def test_mutant_replicated_weight_reports_j004():
    entry = sc.MatrixEntry("13b", 4, "fused", "q40", True)
    res = sc.check_config(
        entry, forward_builder=sc.mutant_replicated_forward(("wcls",)))
    rules = {f.rule for f in res.findings}
    assert "J004" in rules, res.findings
    assert any("wcls" in f.detail for f in res.findings)


def test_replication_hazard_branch_names_the_all_gather():
    # drive the hazard branch directly: expected rows AGREE with the
    # mutant (no drift), so the finding must come from the replicated-
    # weight detector itself
    from distributed_llama_tpu.parallel import tp as tp_mod

    spec = sc.model_spec("13b", "q40")
    closed, params = sc.trace_tp_forward(
        spec, 4, "fused", sc.mutant_replicated_forward(("wcls",)))
    rows = tp_mod.expected_shard_names(params, "fused")
    mutated = [(n, {} if "'wcls'" in n else d) for n, d in rows]
    findings = sc.check_traced_sharding(closed, params, "fused", 4,
                                        "mutant", expected=mutated)
    assert findings and all(f.rule == "J004" for f in findings)
    assert any("REPLICATED" in f.detail for f in findings)


def test_mutant_kv_overshoot_reports_budget_failure():
    # a synth model whose KV cache alone busts the 14.4 GiB usable budget
    spec = small_bench_spec(seq_len=1 << 21,
                            weights_float_type=FloatType.Q40)
    entry = sc.MatrixEntry("synth", 1, "ref", "q40", True)
    res = sc.check_config(entry, spec=spec)
    rules = {f.rule for f in res.findings}
    assert "HBM-BUDGET" in rules, res.findings
    assert not res.report.fits
    assert res.report.kv_cache_bytes > res.report.budget_bytes


def test_declared_unfit_config_that_fits_flags_matrix_drift():
    entry = sc.MatrixEntry("7b", 8, "fused", "q40", False)  # wrong decl
    res = sc.check_config(entry)
    assert any(f.rule == "HBM-BUDGET" and "update the support matrix"
               in f.detail for f in res.findings)


def test_rogue_dequant_reports_j005():
    def rogue(qs, d16):
        lo = (qs & 0xF).astype(jnp.int8) - jnp.int8(8)
        hi = (qs >> 4).astype(jnp.int8) - jnp.int8(8)
        codes = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)
        return (codes * d16.astype(jnp.float32)[..., None]).sum()

    qs = jax.ShapeDtypeStruct((4096, 128, 16), jnp.uint8)
    d16 = jax.ShapeDtypeStruct((4096, 128), jnp.float16)
    closed = jax.make_jaxpr(rogue)(qs, d16)
    findings = sc.check_dequant_sites(closed, "seeded")
    assert findings and all(f.rule == "J005" for f in findings)
    assert "rogue" in findings[0].detail


def test_sanctioned_dequant_does_not_fire_j005():
    # the real forward dequantizes via ops/linear.dequantize_weight (the
    # registered XLA-fallback site) at every Q40 matmul — zero findings
    spec = sc.model_spec("13b", "q40")
    closed, _ = sc.trace_tp_forward(spec, 4, "ref")
    assert sc.check_dequant_sites(closed, "repo") == []


def test_ragged_heads_report_j006():
    spec = small_bench_spec(n_heads=6, n_kv_heads=6)
    findings = sc.check_uniform_shards(spec, 4, "ref", "seeded")
    assert findings and all(f.rule == "J006" for f in findings)
    assert any("n_heads" in f.detail for f in findings)


def test_fused_q40_block_granularity_is_j006():
    # dim/tp not a 32-multiple: the fused scheme cannot slice wo's input
    # blocks — reported as a finding, not a mid-load traceback
    spec = small_bench_spec(dim=448, n_heads=4, n_kv_heads=4,
                            hidden_dim=448)  # 448/4 = 112, not 32-aligned
    findings = sc.check_uniform_shards(spec, 4, "fused", "seeded")
    assert any(f.rule == "J006" and "32-multiple" in f.detail
               for f in findings)


def test_const_hoisted_weight_reports_j004():
    # a weight CLOSED OVER by the body gets hoisted as a shard_map const
    # operand (prepended to in_names, replicated) — it never appears in the
    # declared leaf rows, so the tail-aligned check alone would miss it
    from jax.sharding import PartitionSpec as P

    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.utils.compat import shard_map as _shard_map

    mesh = make_mesh(tp=4, devices=jax.devices()[:4])
    big = jnp.ones((512, 512), jnp.float32)  # 1 MiB, closed over

    def local(x):
        return x + big.sum()

    fn = _shard_map(local, mesh=mesh, in_specs=(P(),), out_specs=P())
    closed = jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8,), jnp.float32))
    findings = sc.check_traced_sharding(closed, None, "ref", 4, "seeded",
                                        expected=[("x", {})])
    assert any(f.rule == "J004" and "hoisted" in f.detail
               for f in findings), findings


# -- registry anti-rot ------------------------------------------------------


def test_dequant_registry_entries_resolve_to_real_functions():
    import importlib

    from distributed_llama_tpu.ops.dequant_sites import ALLOWED_DEQUANT_SITES

    for suffix, fn_name in ALLOWED_DEQUANT_SITES:
        mod_name = ("distributed_llama_tpu."
                    + suffix.replace(".py", "").replace("/", "."))
        mod = importlib.import_module(mod_name)
        assert callable(getattr(mod, fn_name)), (suffix, fn_name)


# -- live-interval walk unit pins ------------------------------------------


def test_live_peak_counts_simultaneous_intermediates():
    def f(x):
        a = x * 2.0          # 1 MB live
        b = x + 1.0          # +1 MB live
        return a + b         # peak: x excluded, a+b+out

    x = jax.ShapeDtypeStruct((256, 1024), jnp.float32)  # 1 MiB
    peak = live_interval_peak(jax.make_jaxpr(f)(x).jaxpr)
    assert peak == 3 * (1 << 20)  # a, b, and the sum live together


def test_live_peak_aliases_in_place_cache_update():
    def f(cache, v):
        return jax.lax.dynamic_update_slice(cache, v, (0, 0))

    cache = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)  # 4 MiB
    v = jax.ShapeDtypeStruct((1, 1024), jnp.float32)
    peak = live_interval_peak(jax.make_jaxpr(f)(cache, v).jaxpr)
    # operand is an (untracked, donated-style) input: in-place, no 4 MiB
    assert peak < (1 << 20)


def test_live_peak_excludes_filtered_eqns():
    def f(x):
        big = x.astype(jnp.float32)  # the "dequant" stand-in
        return big.sum()

    x = jax.ShapeDtypeStruct((1024, 1024), jnp.uint8)
    jaxpr = jax.make_jaxpr(f)(x).jaxpr
    full = live_interval_peak(jaxpr)
    none = live_interval_peak(
        jaxpr, exclude_eqn=lambda e: e.primitive.name
        == "convert_element_type")
    assert full >= 4 * (1 << 20) and none < full


# -- projection + report surfaces ------------------------------------------


def test_projection_carries_hbm_verdict():
    from distributed_llama_tpu.parallel.shard_sim import project_full_system

    spec = sc.model_spec("70b", "q40")
    fits = project_full_system(spec, 8, 10.0, scheme="fused")
    assert fits.hbm_fits and fits.hbm_headroom_gib > 6
    no = project_full_system(spec, 2, 10.0, scheme="fused")
    assert not no.hbm_fits and no.hbm_headroom_gib < 0
    assert no.hbm_per_device_gib > 20


def test_report_json_is_machine_readable(matrix_results):
    rep = sc.report_json(matrix_results)
    assert rep["n_configs"] == 84 and rep["n_violations"] == 0
    assert sum(r["kv_quant"] == "q8" for r in rep["configs"]) == 12
    row = rep["configs"][0]
    assert set(row) >= {"config", "ok", "findings", "report"}
    comp = row["report"]["components_gib"]
    assert set(comp) == {"weights", "replicated", "kv_cache", "activation",
                         "collective"}
    assert row["report"]["total_gib"] == pytest.approx(
        sum(comp.values()), abs=0.01)


def test_staging_term_tracks_the_budget_cut_points():
    from distributed_llama_tpu.parallel.comm_stats import (
        collective_staging_bytes)

    spec = sc.model_spec("70b", "q40")
    assert collective_staging_bytes(spec, 1, "ref") == 0
    ref = collective_staging_bytes(spec, 8, "ref")
    fused = collective_staging_bytes(spec, 8, "fused")
    # both schemes' largest payload is the f32 logits gather at these dims
    assert ref == fused == 2 * 32000 * 4
    # Q80 buffers shrink the ref gathers but never the f32 logits
    spec80 = dataclasses.replace(spec, buffer_float_type=FloatType.Q80)
    assert collective_staging_bytes(spec80, 8, "ref") == 2 * 32000 * 4
