"""Comm accounting vs the reference's published transfer tables (README.md:58-69).

The star-topology accounting must reproduce the reference's measured root-side
S/R bytes per token — a strong check that we understand its collective
structure (and therefore that our all_gather mapping covers the same data)."""

import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType
from distributed_llama_tpu.parallel.comm_stats import (ici_all_gather_bytes,
                                                       reference_star_bytes)

L7B = dict(dim=4096, hidden_dim=11008, n_layers=32, n_heads=32, n_kv_heads=32,
           vocab_size=32000, seq_len=2048)
L13B = dict(dim=5120, hidden_dim=13824, n_layers=40, n_heads=40, n_kv_heads=40,
            vocab_size=32000, seq_len=2048)
L70B = dict(dim=8192, hidden_dim=28672, n_layers=80, n_heads=64, n_kv_heads=8,
            vocab_size=32000, seq_len=2048)


def _spec(cfg, bft):
    return TransformerSpec(**cfg, buffer_float_type=bft)


@pytest.mark.parametrize("cfg,n,s_kb,r_kb", [
    (L7B, 2, 2224, 1968),    # README.md:58
    (L13B, 2, 3480, 3080),   # README.md:59
])
def test_star_f32_published(cfg, n, s_kb, r_kb):
    st = reference_star_bytes(_spec(cfg, FloatType.F32), n)
    assert abs(st.sent_bytes / 1024 - s_kb) / s_kb < 0.01
    assert abs(st.recv_bytes / 1024 - r_kb) / r_kb < 0.01


@pytest.mark.parametrize("cfg,n,total_kb", [
    (L7B, 2, 1112), (L7B, 4, 2830), (L7B, 8, 6008),     # README.md:67
    (L13B, 2, 1742), (L13B, 4, 4430), (L13B, 8, 9407),  # README.md:68
])
def test_star_q80_published(cfg, n, total_kb):
    st = reference_star_bytes(_spec(cfg, FloatType.Q80), n)
    total = (st.sent_bytes + st.recv_bytes) / 1024
    assert abs(total - total_kb) / total_kb < 0.02


def test_star_q80_70b_published():
    st = reference_star_bytes(_spec(L70B, FloatType.Q80), 8)
    # README.md:69: S 28857 / R 4016 kB
    assert abs(st.sent_bytes / 1024 - 28857) / 28857 < 0.02
    assert abs(st.recv_bytes / 1024 - 4016) / 4016 < 0.02


def test_ici_scheme_moves_less_than_star():
    """Our all_gather scheme must beat the reference's star wire volume."""
    for cfg in (L7B, L13B, L70B):
        for n in (2, 4, 8):
            spec = _spec(cfg, FloatType.Q80)
            ours = ici_all_gather_bytes(spec, n)
            star = reference_star_bytes(spec, n)
            assert (ours.sent_bytes + ours.recv_bytes) < (
                star.sent_bytes + star.recv_bytes)


def test_single_slice_no_comm():
    st = ici_all_gather_bytes(_spec(L7B, FloatType.F32), 1)
    assert st.sent_bytes == 0 and st.recv_bytes == 0
