"""Comm accounting vs the reference's published transfer tables (README.md:58-69).

The star-topology accounting must reproduce the reference's measured root-side
S/R bytes per token — a strong check that we understand its collective
structure (and therefore that our all_gather mapping covers the same data)."""

import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType, batch_bytes
from distributed_llama_tpu.parallel.comm_stats import (ici_all_gather_bytes,
                                                       reference_star_bytes,
                                                       tp_collective_budget,
                                                       tp_scheme)

L7B = dict(dim=4096, hidden_dim=11008, n_layers=32, n_heads=32, n_kv_heads=32,
           vocab_size=32000, seq_len=2048)
L13B = dict(dim=5120, hidden_dim=13824, n_layers=40, n_heads=40, n_kv_heads=40,
            vocab_size=32000, seq_len=2048)
L70B = dict(dim=8192, hidden_dim=28672, n_layers=80, n_heads=64, n_kv_heads=8,
            vocab_size=32000, seq_len=2048)


def _spec(cfg, bft):
    return TransformerSpec(**cfg, buffer_float_type=bft)


@pytest.mark.parametrize("cfg,n,s_kb,r_kb", [
    (L7B, 2, 2224, 1968),    # README.md:58
    (L13B, 2, 3480, 3080),   # README.md:59
])
def test_star_f32_published(cfg, n, s_kb, r_kb):
    st = reference_star_bytes(_spec(cfg, FloatType.F32), n)
    assert abs(st.sent_bytes / 1024 - s_kb) / s_kb < 0.01
    assert abs(st.recv_bytes / 1024 - r_kb) / r_kb < 0.01


@pytest.mark.parametrize("cfg,n,total_kb", [
    (L7B, 2, 1112), (L7B, 4, 2830), (L7B, 8, 6008),     # README.md:67
    (L13B, 2, 1742), (L13B, 4, 4430), (L13B, 8, 9407),  # README.md:68
])
def test_star_q80_published(cfg, n, total_kb):
    st = reference_star_bytes(_spec(cfg, FloatType.Q80), n)
    total = (st.sent_bytes + st.recv_bytes) / 1024
    assert abs(total - total_kb) / total_kb < 0.02


def test_star_q80_70b_published():
    st = reference_star_bytes(_spec(L70B, FloatType.Q80), 8)
    # README.md:69: S 28857 / R 4016 kB
    assert abs(st.sent_bytes / 1024 - 28857) / 28857 < 0.02
    assert abs(st.recv_bytes / 1024 - 4016) / 4016 < 0.02


def test_ici_scheme_moves_less_than_star():
    """The ref scheme must beat the reference's star wire volume at every
    size; the fused scheme beats it wherever the star's O(S^2) hb
    all-gather exists (n >= 4). At n=2 under Q80 the fused combine's f32
    reduce halves genuinely move more bytes than the star — the model
    records the trade instead of hiding it (fused buys launch count, and
    its default pairing is the f32 buffer mode, where it also wins bytes:
    test_fused_f32_moves_less_than_ref_f32)."""
    for cfg in (L7B, L13B, L70B):
        for n in (2, 4, 8):
            spec = _spec(cfg, FloatType.Q80)
            star = reference_star_bytes(spec, n)
            star_total = star.sent_bytes + star.recv_bytes
            ours = ici_all_gather_bytes(spec, n, "ref")
            assert (ours.sent_bytes + ours.recv_bytes) < star_total
            if n >= 4:
                fused = ici_all_gather_bytes(spec, n, "fused")
                assert (fused.sent_bytes + fused.recv_bytes) < star_total


def test_overlap_budget_analytic_pins():
    """Pin the overlap scheme's analytic count AND bytes (ISSUE 10): per
    layer, the two ring-decomposed combines issue 2*(S-1) single-hop
    ppermutes — per-chip bytes exactly the fused reduce_scatter's
    (S-1)/S of the f32 payload — plus 2 band gathers (packed Q80 bytes
    under the Q80 wire, f32 under f32), plus the logits gather. Total
    bytes EQUAL the fused scheme's (the decomposition moves the same
    data); only the launch structure changes — which is the point: each
    launch is a hideable hop."""
    from distributed_llama_tpu.parallel.comm_stats import collective_hops

    spec = _spec(L7B, FloatType.F32)
    s, L, dim = 8, spec.n_layers, spec.dim
    b = tp_collective_budget(spec, s, "overlap")
    assert b.kind_counts() == {"ppermute": 2 * L * (s - 1),
                               "all_gather": 2 * L + 1}
    pp_bytes = 2 * L * (s - 1) * (dim // s) * 4
    ag_bytes = 2 * L * (s - 1) * (dim // s) * 4
    logits_bytes = (s - 1) * (spec.vocab_size // s) * 4
    assert b.moved_bytes == pp_bytes + ag_bytes + logits_bytes
    assert b.moved_bytes == tp_collective_budget(spec, s,
                                                 "fused").moved_bytes

    spec80 = _spec(L7B, FloatType.Q80)
    b80 = tp_collective_budget(spec80, s, "overlap")
    assert b80.kind_counts() == {"ppermute": 2 * L * (s - 1),
                                 "all_gather": 2 * L + 1}
    ag80 = 2 * L * (s - 1) * batch_bytes(FloatType.Q80, dim // s)
    assert b80.moved_bytes == pp_bytes + ag80 + logits_bytes
    assert b80.moved_bytes == tp_collective_budget(spec80, s,
                                                   "fused").moved_bytes

    # hop accounting: a ppermute launch is ONE hop, ring collectives S-1
    assert collective_hops("ppermute", s) == 1
    assert collective_hops("all_gather", s) == s - 1
    assert collective_hops("psum", s) == s - 1


def test_overlap_staging_adds_double_buffer_charge():
    """The chunked-staging HBM term: overlap = the fused in-flight bound
    PLUS two deferred-gather buffers (the double-buffered wire cut)."""
    from distributed_llama_tpu.parallel.comm_stats import (
        collective_staging_bytes)

    for ft in (FloatType.F32, FloatType.Q80):
        spec = _spec(L7B, ft)
        fused = collective_staging_bytes(spec, 8, "fused")
        over = collective_staging_bytes(spec, 8, "overlap")
        pend = batch_bytes(ft if ft == FloatType.Q80 else FloatType.F32,
                           spec.dim)
        assert over == fused + 2 * pend
    assert collective_staging_bytes(_spec(L7B, FloatType.F32), 1,
                                    "overlap") == 0


def test_single_slice_no_comm():
    for scheme in ("ref", "fused", "overlap"):
        st = ici_all_gather_bytes(_spec(L7B, FloatType.F32), 1, scheme)
        assert st.sent_bytes == 0 and st.recv_bytes == 0
        assert tp_collective_budget(_spec(L7B, FloatType.F32), 1,
                                    scheme).n_collectives == 0


def test_tp_scheme_env(monkeypatch):
    monkeypatch.delenv("DLLAMA_TP_SCHEME", raising=False)
    assert tp_scheme() == "fused"  # the fastest policy is the default
    monkeypatch.setenv("DLLAMA_TP_SCHEME", "ref")
    assert tp_scheme() == "ref"
    monkeypatch.setenv("DLLAMA_TP_SCHEME", "megatron")
    with pytest.raises(ValueError, match="DLLAMA_TP_SCHEME"):
        tp_scheme()


def test_fused_budget_analytic_pins():
    """Pin the fused scheme's analytic count AND bytes (the ISSUE 3
    satellite): per layer, f32 buffers issue 2 psums of the full dim
    vector (ring all-reduce: 2*(S-1)/S of the payload per chip) and Q80
    buffers decompose each into a f32 psum_scatter ((S-1)/S) + a packed
    Q80 gather of the dim/S shard ((S-1) shards). Counts: f32 2L+1 vs the
    ref scheme's 4L+1; Q80 4L+1 with the wire payload preserved."""
    spec = _spec(L7B, FloatType.F32)
    s, L, dim = 8, spec.n_layers, spec.dim

    b = tp_collective_budget(spec, s, "fused")
    assert b.kind_counts() == {"psum": 2 * L, "all_gather": 1}
    assert b.n_collectives == 2 * L + 1
    psum_bytes = 2 * L * 2 * (s - 1) * (dim // s) * 4
    logits_bytes = (s - 1) * (spec.vocab_size // s) * 4
    assert b.moved_bytes == psum_bytes + logits_bytes

    spec80 = _spec(L7B, FloatType.Q80)
    b80 = tp_collective_budget(spec80, s, "fused")
    assert b80.kind_counts() == {"reduce_scatter": 2 * L,
                                 "all_gather": 2 * L + 1}
    assert b80.n_collectives == 4 * L + 1
    rs_bytes = 2 * L * (s - 1) * (dim // s) * 4
    ag_bytes = 2 * L * (s - 1) * batch_bytes(FloatType.Q80, dim // s)
    assert b80.moved_bytes == rs_bytes + ag_bytes + logits_bytes

    # ref pins, same one-source-of-truth structure
    r = tp_collective_budget(spec, s, "ref")
    assert r.kind_counts() == {"all_gather": 4 * L + 1}
    assert r.n_collectives == 4 * L + 1
    # and the historic entry point agrees with the budget per scheme
    for scheme in ("ref", "fused"):
        assert ici_all_gather_bytes(spec, s, scheme).sent_bytes == \
            tp_collective_budget(spec, s, scheme).moved_bytes


def test_fused_f32_moves_less_than_ref_f32():
    """On every real shape the fused scheme wins BOTH terms under f32
    buffers: half the per-layer collectives (latency) and fewer bytes
    (4/S·... of 2·dim vs 3·dim+hidden per layer, bandwidth)."""
    for cfg in (L7B, L13B, L70B):
        for n in (2, 4, 8):
            spec = _spec(cfg, FloatType.F32)
            fused = tp_collective_budget(spec, n, "fused")
            ref = tp_collective_budget(spec, n, "ref")
            assert fused.n_collectives < ref.n_collectives
            assert fused.moved_bytes < ref.moved_bytes
