"""Pallas Q40 matmul kernel vs the XLA dequantize-then-dot path.

Runs in interpret mode on CPU; the same kernel compiles for TPU (where the
bench uses it). Parity must be tight: both paths consume the identical Q40
value map in f32."""

import numpy as np
import pytest

from distributed_llama_tpu.io.loader import Q40Weight
from distributed_llama_tpu.ops.quants import dequantize_q40, quantize_q40


def _mk(d, n, seed=0):
    rng = np.random.default_rng(seed)
    w = (rng.standard_normal((d, n)) * 0.3).astype(np.float32)
    qs, d16 = quantize_q40(w)
    return Q40Weight(qs, d16)


@pytest.mark.parametrize("d,n,t", [(256, 512, 1), (512, 256, 4),
                                   (384, 1024, 2)])
def test_kernel_matches_dequant_dot(d, n, t):
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    w = _mk(d, n)
    rng = np.random.default_rng(1)
    x = rng.standard_normal((t, n)).astype(np.float32)

    want = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x.T  # (d, t)
    got = q40_matmul(w, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want.T, rtol=1e-5, atol=1e-4)


def test_kernel_1d_input():
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    w = _mk(128, 256, seed=3)
    x = np.random.default_rng(2).standard_normal(256).astype(np.float32)
    want = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x
    got = q40_matmul(w, jnp.asarray(x))
    assert got.shape == (128,)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-4)


def test_matmul_dispatch_prefer_pallas():
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.linear import matmul

    w = _mk(128, 128, seed=5)
    x = np.random.default_rng(4).standard_normal(128).astype(np.float32)
    a = matmul(w, jnp.asarray(x))
    b = matmul(w, jnp.asarray(x), prefer_pallas=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-4)
