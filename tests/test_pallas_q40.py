"""Pallas Q40 matmul kernel vs the XLA dequantize-then-dot path.

Runs in interpret mode on CPU; the same kernel compiles for TPU (where the
bench uses it). Parity must be tight: both paths consume the identical Q40
value map in f32."""

import numpy as np
import pytest

from distributed_llama_tpu.io.loader import Q40Weight
from distributed_llama_tpu.ops.quants import dequantize_q40, quantize_q40


def _mk(d, n, seed=0):
    rng = np.random.default_rng(seed)
    w = (rng.standard_normal((d, n)) * 0.3).astype(np.float32)
    qs, d16 = quantize_q40(w)
    return Q40Weight(qs, d16)


@pytest.mark.parametrize("d,n,t", [(256, 512, 1), (512, 256, 4),
                                   (384, 1024, 2)])
def test_kernel_matches_dequant_dot(d, n, t):
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    w = _mk(d, n)
    rng = np.random.default_rng(1)
    x = rng.standard_normal((t, n)).astype(np.float32)

    want = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x.T  # (d, t)
    got = q40_matmul(w, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want.T, rtol=1e-5, atol=1e-4)


def test_kernel_1d_input():
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    w = _mk(128, 256, seed=3)
    x = np.random.default_rng(2).standard_normal(256).astype(np.float32)
    want = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x
    got = q40_matmul(w, jnp.asarray(x))
    assert got.shape == (128,)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-4)


def test_kernel_layout_roundtrip():
    from distributed_llama_tpu.io.loader import (from_kernel_layout,
                                                 to_kernel_layout)

    w = _mk(64, 128, seed=7)
    wk = to_kernel_layout(w)
    assert wk.qs_t.shape == (16, 64, 4)
    assert wk.scale.dtype == np.float32
    assert wk.logical_shape == (64, 128)
    back = from_kernel_layout(wk)
    np.testing.assert_array_equal(np.asarray(back.qs), np.asarray(w.qs))
    np.testing.assert_array_equal(np.asarray(back.d16), np.asarray(w.d16))


def test_kernel_accepts_pretiled_layout():
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import to_kernel_layout
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    w = _mk(128, 256, seed=9)
    x = np.random.default_rng(8).standard_normal((3, 256)).astype(np.float32)
    a = q40_matmul(w, jnp.asarray(x))
    b = q40_matmul(to_kernel_layout(w), jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pack_q40_params_and_forward_parity(monkeypatch):
    """Forward with kernel-tiled Q40 params (Pallas interpret) must match the
    XLA dequantize-then-dot forward on the same codec-layout params."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import Q40Kernel
    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.ops.quants import FloatType

    spec = TransformerSpec(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=128, seq_len=32,
                           weights_float_type=FloatType.Q40)
    from distributed_llama_tpu.models.synth import synth_params

    params = synth_params(spec, q40=True, seed=11, scale=0.2)
    tok = jnp.asarray([5], dtype=jnp.int32)

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "xla")
    ref_logits, _ = forward(spec, params_to_device(params), init_cache(spec),
                            tok, jnp.int32(0))

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    packed = params_to_device(params)
    # packing AND qkv/w13 fusion actually happened
    assert isinstance(packed["wqkv"], Q40Kernel)
    assert isinstance(packed["w13"], Q40Kernel)
    assert "wq" not in packed and "w1" not in packed
    assert packed["wqkv"].logical_shape == (
        spec.n_layers, spec.dim + 2 * spec.n_kv_heads * spec.head_size,
        spec.dim)
    got_logits, _ = forward(spec, packed, init_cache(spec), tok, jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got_logits), np.asarray(ref_logits),
                               rtol=2e-5, atol=2e-5)


def test_tp_sharded_forward_with_kernel_layout(monkeypatch):
    """Tensor-parallel forward with kernel-tiled Q40 weights (the TPU deploy
    configuration) must match tp=1 XLA-path logits — exercises the Q40Kernel
    branch of param_specs and the kernel inside shard_map (interpret mode)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import Q40Kernel
    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.ops.quants import FloatType
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    spec = TransformerSpec(dim=128, hidden_dim=256, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=256, seq_len=32,
                           weights_float_type=FloatType.Q40)
    params = synth_params(spec, q40=True, seed=13, scale=0.2)
    tok = jnp.asarray([3], dtype=jnp.int32)

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "xla")
    ref_logits, _ = forward(spec, params_to_device(params), init_cache(spec),
                            tok, jnp.int32(0))

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    # forcing the attention kernel here exercises the supports() gate's
    # unsupported-shape fallback (head_size 32 fails the %128 check, so the
    # XLA attention path must engage); the kernel-engaged TP case is
    # test_tp_sharded_forward_with_flash_attention below
    monkeypatch.setenv("DLLAMA_ATTN_KERNEL", "pallas")
    mesh = make_mesh(tp=2)
    sharded = shard_params(params, mesh)
    assert isinstance(sharded["wq"], Q40Kernel)  # packed + sharded
    fwd = make_sharded_forward(spec, mesh)
    got_logits, _ = fwd(sharded, shard_cache(init_cache(spec), mesh), tok,
                        jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got_logits[0]),
                               np.asarray(ref_logits[0]),
                               rtol=2e-5, atol=2e-5)


def test_matmul_dispatch_prefer_pallas():
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.linear import matmul

    w = _mk(128, 128, seed=5)
    x = np.random.default_rng(4).standard_normal(128).astype(np.float32)
    a = matmul(w, jnp.asarray(x))
    b = matmul(w, jnp.asarray(x), prefer_pallas=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                               atol=1e-4)


def test_tp_shard_dims_keep_matvec_kernel_and_fallback_for_big_t():
    """d = 11008/tp8 = 1376 has no multiple-of-128 divisor: the T=1 matvec
    path must still tile it (kernel_supports gates packing on T=1 only), and
    big-T calls must fall back to dequantize-then-dot INSIDE q40_matmul
    instead of raising."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import Q40Weight, to_kernel_layout
    from distributed_llama_tpu.ops.pallas_q40 import (kernel_supports,
                                                      q40_matmul)
    from distributed_llama_tpu.ops.quants import quantize_q40

    d, n = 1376, 256
    assert kernel_supports(d, n)
    rng = np.random.default_rng(3)
    wf = (rng.standard_normal((d, n)) * 0.1).astype(np.float32)
    qs, d16 = quantize_q40(wf)
    w = to_kernel_layout(Q40Weight(qs, d16))

    from distributed_llama_tpu.ops.linear import dequantize_weight

    wref = np.asarray(dequantize_weight(Q40Weight(qs, d16)))
    for t in (1, 12):  # matvec kernel; MXU-untileable -> internal fallback
        x = (rng.standard_normal((t, n)) * 0.5).astype(np.float32)
        got = np.asarray(q40_matmul(w, jnp.asarray(x), interpret=True))
        np.testing.assert_allclose(got, x @ wref.T, rtol=2e-4, atol=2e-4)


def test_mxu_path_pads_awkward_t():
    """T > MULTI_T_MAX and not a multiple of 8 must pad (a full-T tile of
    awkward length can exceed the scoped-VMEM plane budget) and still match
    the dequant reference."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_q40 import MULTI_T_MAX, q40_matmul

    w = _mk(256, 512, seed=21)
    t = MULTI_T_MAX + 5  # 13: not a multiple of 8
    rng = np.random.default_rng(22)
    x = rng.standard_normal((t, 512)).astype(np.float32)
    want = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x.T
    got = q40_matmul(w, jnp.asarray(x))
    assert got.shape == (t, 256)
    np.testing.assert_allclose(np.asarray(got), want.T, rtol=1e-5, atol=1e-4)


def test_tp_sharded_forward_with_flash_attention(monkeypatch):
    """TP forward with the flash-decode attention kernel ACTUALLY engaged
    (head_size 128 — the supports() gate; per-shard local kv heads)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.ops.pallas_attention import supports
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    spec = TransformerSpec(dim=512, hidden_dim=256, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=256, seq_len=32)
    # mirror the production gate exactly (f32 cache itemsize = 4)
    assert supports(spec.seq_len, spec.head_size, 1, spec.n_kv_heads // 2, 4)
    params = synth_params(spec, q40=False, seed=17, scale=0.1)

    monkeypatch.setenv("DLLAMA_ATTN_KERNEL", "pallas")
    mesh = make_mesh(tp=2)
    fwd = make_sharded_forward(spec, mesh)
    # decode a few positions so the kernel sees a partly-filled cache
    cache = shard_cache(init_cache(spec), mesh)
    sharded = shard_params(params, mesh)
    lg = None
    for pos, t in enumerate([3, 9, 44]):
        lg, cache = fwd(sharded, cache, jnp.asarray([t], jnp.int32),
                        jnp.int32(pos))
    # reference: same chain through the single-chip XLA path
    monkeypatch.setenv("DLLAMA_ATTN_KERNEL", "xla")
    c2 = init_cache(spec)
    p2 = params_to_device(params)
    want = None
    for pos, t in enumerate([3, 9, 44]):
        want, c2 = forward(spec, p2, c2, jnp.asarray([t], jnp.int32),
                           jnp.int32(pos))
    np.testing.assert_allclose(np.asarray(lg[0]), np.asarray(want[0]),
                               rtol=2e-5, atol=2e-5)


def test_matvec_tile_vmem_cap_on_wide_inputs():
    """70B-shard regression: the T=1 matvec tiler must cap rows*nb so the
    double-buffered tile set (16 u8 planes + f32 scale per (row, block))
    stays under the 16 MB scoped-VMEM limit. At nb=896 (w2's hidden/8 =
    28672-wide input) an uncapped 512-row tile measured 17.5 MB and the
    kernel failed to COMPILE on the real chip — the bench then silently
    recorded the 3x-slower XLA fallback."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_q40 import (_pick_block_rows,
                                                      q40_matmul)

    rows = _pick_block_rows(1024, 1, 896)
    assert rows is not None and rows * 896 <= 360_000
    # 7B/13B tilings unchanged by the cap (nb <= 432 never binds: the 768
    # top is the binding limit there)
    assert _pick_block_rows(4096, 1, 344) == 512  # 7B w2, as in round 1
    for d, nb in ((4096, 128), (11008, 128), (4096, 344), (5120, 160)):
        r = _pick_block_rows(d, 1, nb)
        assert r is not None and r * nb <= 360_000

    # correctness at the capped tiling (interpret mode; the REAL 70B w2
    # band shape d=1024, so the cap actually binds: rows=256+grid, not a
    # single full-d tile)
    w = _mk(1024, 28672)
    x = np.random.default_rng(3).standard_normal((1, 28672)).astype(
        np.float32)
    want = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x.T
    got = q40_matmul(w, jnp.asarray(x), interpret=True)
    np.testing.assert_allclose(np.asarray(got), want.T, rtol=1e-4, atol=1e-3)


def test_bf16_mode_not_served_from_parity_trace_cache():
    """The jitted kernel wrappers key their trace cache on the precision
    flag: tracing parity FIRST then bf16 must produce a bf16 result, not a
    silently-reused parity trace (the contextvar alone is invisible to the
    jit cache — the round-2 bug that made --fast-prefill a no-op)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.linear import matmul_precision
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    w = _mk(256, 512, seed=7)
    x = jnp.asarray(np.random.default_rng(5).standard_normal(
        (16, 512)).astype(np.float32) * 2.0)

    parity = np.asarray(q40_matmul(w, x, interpret=True))   # caches traces
    with matmul_precision("bf16"):
        fast = np.asarray(q40_matmul(w, x, interpret=True))
    # bf16 rounding must be VISIBLE (different result) but small
    diff = np.abs(parity - fast).max()
    scale = np.abs(parity).max()
    assert 0 < diff < 0.03 * scale


def test_nbmajor_matvec_matches_dequant():
    """nb-major (Q40KernelNb) T=1 kernel parity on a 13B-like shape whose
    block count pads badly in the standard layout (n=5120 -> nb=160)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import (from_kernel_layout_nb,
                                                 to_kernel_layout_nb)
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    w = _mk(256, 5120, seed=21)
    wn = to_kernel_layout_nb(w)
    assert wn.qs_t.shape == (16, 160, 256)
    assert wn.logical_shape == (256, 5120)
    back = from_kernel_layout_nb(wn)
    np.testing.assert_array_equal(np.asarray(back.qs), np.asarray(w.qs))
    np.testing.assert_array_equal(np.asarray(back.d16), np.asarray(w.d16))

    x = np.random.default_rng(2).standard_normal((1, 5120)).astype(np.float32)
    want = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x.T
    got = q40_matmul(wn, x, interpret=True)
    np.testing.assert_allclose(np.asarray(got), want.T, rtol=1e-4, atol=1e-3)

    # the full dispatch ladder: T=2/4 (VPU multi-nb kernel — T=5..8 take
    # the dequant fallback, whose scoped-VMEM footprint was measured to
    # overflow), T=6 (that fallback), T=16 (MXU body), T=13 (pads to 16)
    wd = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16))
    for t in (2, 4, 6, 16, 13):
        xt = np.random.default_rng(t).standard_normal((t, 5120)).astype(
            np.float32)
        got_t = q40_matmul(wn, xt, interpret=True)
        np.testing.assert_allclose(np.asarray(got_t), (wd @ xt.T).T,
                                   rtol=1e-4, atol=1e-3)


def test_nbmajor_pack_selection_and_forward_parity(monkeypatch):
    """pack_q40_params must pick nb-major exactly for badly-padding shapes
    at tp=1 (13B's nb=160 -> 1.6x; 7B's nb=128/344 stays d-major), and the
    full forward through stacked nb-major weights (scalar-prefetch scan)
    must match the XLA path."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import Q40Kernel, Q40KernelNb
    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.ops.linear import pack_q40_params
    from distributed_llama_tpu.ops.quants import FloatType

    # dim 128 -> per-layer matmul inputs n=128 (nb=4 -> ratio 32: nb-major
    # needs d%128==0 which holds) BUT tiny nb also passes the ratio gate; use
    # hidden chosen so w1/w3 (n=128) and w2 (n=5120-like)... simpler: pin on
    # a 13B-dim-shaped single tensor tree
    spec = TransformerSpec(dim=128, hidden_dim=1280, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=256, seq_len=16,
                           weights_float_type=FloatType.Q40)
    params = synth_params(spec, q40=True, seed=31, scale=0.2)
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "xla")
    tok = jnp.asarray([5], dtype=jnp.int32)
    ref_logits, _ = forward(spec, params_to_device(params), init_cache(spec),
                            tok, jnp.int32(0))

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    packed = pack_q40_params(synth_params(spec, q40=True, seed=31,
                                          scale=0.2), allow_nb_major=True)
    # w2 consumes hidden=1280 -> nb=40 -> pads to 128 (3.2x): nb-major
    assert isinstance(packed["w2"], Q40KernelNb)
    # wq consumes dim=128 -> nb=4... also nb-major (ratio 32x); the point:
    # selection keys on the pad ratio, not the tensor name
    assert isinstance(packed["wq"], Q40KernelNb)

    dev = params_to_device(synth_params(spec, q40=True, seed=31, scale=0.2))
    got_logits, _ = forward(spec, dev, init_cache(spec), tok, jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got_logits),
                               np.asarray(ref_logits), rtol=2e-5, atol=2e-5)

    # 7B/70B shapes keep the tuned d-major layout even when allowed
    p7 = pack_q40_params({"wq": _mk(256, 4096)}, allow_nb_major=True)
    assert isinstance(p7["wq"], Q40Kernel)     # nb=128: no padding
    p7b = pack_q40_params({"w2": _mk(256, 11008)}, allow_nb_major=True)
    assert isinstance(p7b["w2"], Q40Kernel)    # nb=344: 1.12x only
    # and WITHOUT the single-chip opt-in nothing goes nb-major (sharded
    # callers: an sp>1 mesh packs with tp=1 but cannot carry Q40KernelNb)
    psh = pack_q40_params({"w2": _mk(128, 1280)})  # nb=40: 3.2x ratio
    assert isinstance(psh["w2"], Q40Kernel)


@pytest.mark.parametrize("layout", ["d_major", "nb_major"])
@pytest.mark.parametrize("mode", ["legacy", "scratch", "dequant"])
def test_prefill_matmul_modes_match(mode, layout, monkeypatch):
    """The three T>8 prefill strategies (DLLAMA_PREFILL_MATMUL) compute the
    same product on both kernel layouts: legacy (t-outer grid), scratch
    (d-outer grid, unpack-once into VMEM scratch), dequant (HBM temp +
    XLA dot)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import to_kernel_layout_nb
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    monkeypatch.setenv("DLLAMA_PREFILL_MATMUL", mode)
    if layout == "nb_major":
        d, n, t = 256, 5120, 32   # 13B-like badly-padding block count
        w = _mk(d, n, seed=11)
        wk = to_kernel_layout_nb(w)
    else:
        d, n, t = 256, 512, 32
        w = wk = _mk(d, n, seed=11)
    x = np.random.default_rng(12).standard_normal((t, n)).astype(np.float32)
    want = dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x.T
    got = q40_matmul(wk, jnp.asarray(x), interpret=True)
    np.testing.assert_allclose(np.asarray(got), want.T, rtol=1e-4, atol=1e-3)


def test_prefill_scratch_stacked_matches(monkeypatch):
    """Stacked (lax.scan layer-indexed) scratch kernel parity."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import to_kernel_layout
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    monkeypatch.setenv("DLLAMA_PREFILL_MATMUL", "scratch")
    d, n, t, L = 128, 256, 16, 3
    ws = [_mk(d, n, seed=20 + i) for i in range(L)]
    ks = [to_kernel_layout(w) for w in ws]
    from distributed_llama_tpu.io.loader import Q40Kernel

    stacked = Q40Kernel(np.stack([np.asarray(k.qs_t) for k in ks]),
                        np.stack([np.asarray(k.scale) for k in ks]))
    x = np.random.default_rng(30).standard_normal((t, n)).astype(np.float32)
    for layer in range(L):
        want = dequantize_q40(np.asarray(ws[layer].qs),
                              np.asarray(ws[layer].d16)) @ x.T
        got = q40_matmul(stacked, jnp.asarray(x), layer=jnp.int32(layer))
        np.testing.assert_allclose(np.asarray(got), want.T,
                                   rtol=1e-5, atol=1e-4)


@pytest.mark.parametrize("d,n,t", [(256, 512, 8), (384, 1024, 4),
                                   (512, 256, 2)])
def test_multi_dequant_body_matches(d, n, t, monkeypatch):
    """DLLAMA_MULTI_T_BODY=dequant (VERDICT r4 #6): the one-dot MXU body
    agrees with the dequantized reference at the documented bf16
    tolerance (bf16 multiply, f32 accumulation)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    monkeypatch.setenv("DLLAMA_MULTI_T_BODY", "dequant")
    w = _mk(d, n)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((t, n)).astype(np.float32)

    want = (dequantize_q40(np.asarray(w.qs), np.asarray(w.d16)) @ x.T).T
    got = q40_matmul(w, jnp.asarray(x))
    assert got.shape == (t, d)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-2,
                               atol=0.15)


def test_multi_dequant_body_stacked_matches(monkeypatch):
    """Stacked-layer (scan) variant of the one-dot body, via the layer-
    indexed dispatch."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import to_kernel_layout
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul

    monkeypatch.setenv("DLLAMA_MULTI_T_BODY", "dequant")
    L, d, n, t = 3, 256, 512, 8
    rng = np.random.default_rng(4)
    ws = [_mk(d, n, seed=10 + i) for i in range(L)]
    stacked = Q40Weight(np.stack([np.asarray(w.qs) for w in ws]),
                        np.stack([np.asarray(w.d16) for w in ws]))
    kern = to_kernel_layout(stacked)
    x = rng.standard_normal((t, n)).astype(np.float32)
    for layer in range(L):
        want = (dequantize_q40(np.asarray(ws[layer].qs),
                               np.asarray(ws[layer].d16)) @ x.T).T
        got = q40_matmul(kern, jnp.asarray(x), layer=layer)
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-2,
                                   atol=0.15)


def test_multi_t_body_env_validation(monkeypatch):
    from distributed_llama_tpu.ops.pallas_q40 import _multi_t_body

    monkeypatch.setenv("DLLAMA_MULTI_T_BODY", "mxu")
    with pytest.raises(ValueError, match="DLLAMA_MULTI_T_BODY"):
        _multi_t_body()
    monkeypatch.setenv("DLLAMA_MULTI_T_BODY", "")
    assert _multi_t_body() == "vpu"


@pytest.mark.parametrize("layout", ["d", "nb"])
def test_i4_planes_matvec_matches_u8(layout, monkeypatch):
    """to_i4_planes + the int4 matvec bodies (DLLAMA_Q40_I4) compute the
    exact same integers as the u8 kernels: parity is f32-tight."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import (to_kernel_layout,
                                                 to_kernel_layout_nb)
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul, to_i4_planes

    d, n = 256, 512
    w = _mk(d, n, seed=3)
    kern = to_kernel_layout(w) if layout == "d" else to_kernel_layout_nb(w)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((1, n)).astype(np.float32))

    want = np.asarray(q40_matmul(kern, x))
    got = np.asarray(jax.jit(
        lambda k, xv: q40_matmul(to_i4_planes(k), xv))(kern, x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_i4_planes_stacked_and_fallbacks(monkeypatch):
    """Stacked (layer-indexed) int4 dispatch + the T>1 dequant fallback
    agree with the u8 reference."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import to_kernel_layout
    from distributed_llama_tpu.ops.pallas_q40 import q40_matmul, to_i4_planes

    L, d, n = 2, 256, 512
    ws = [_mk(d, n, seed=20 + i) for i in range(L)]
    stacked = to_kernel_layout(Q40Weight(
        np.stack([np.asarray(w.qs) for w in ws]),
        np.stack([np.asarray(w.d16) for w in ws])))
    rng = np.random.default_rng(6)
    x1 = jnp.asarray(rng.standard_normal((1, n)).astype(np.float32))
    xt = jnp.asarray(rng.standard_normal((4, n)).astype(np.float32))
    for layer in range(L):
        want = np.asarray(q40_matmul(stacked, x1, layer=layer))
        got = np.asarray(jax.jit(
            lambda k, xv, la=layer: q40_matmul(to_i4_planes(k), xv,
                                               layer=la))(stacked, x1))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # T>1: the dequant fallback (per-layer slice of the stacked planes)
    want = dequantize_q40(np.asarray(ws[1].qs), np.asarray(ws[1].d16)) \
        @ np.asarray(xt).T
    got = np.asarray(jax.jit(
        lambda k, xv: q40_matmul(to_i4_planes(k), xv, layer=1))(stacked, xt))
    np.testing.assert_allclose(got, want.T, rtol=1e-4, atol=1e-3)


def test_i4_decode_chain_parity(monkeypatch):
    """DLLAMA_Q40_I4=on: the fused decode chain produces the same tokens
    and cache as the u8 path (the conversion is inside the chain; same
    integers end to end)."""
    import functools as ft

    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache
    from distributed_llama_tpu.models.synth import small_bench_spec, synth_params
    from distributed_llama_tpu.ops.linear import (fuse_q40_layer_matmuls,
                                                  pack_q40_params)
    from distributed_llama_tpu.runtime.decode import make_decode_loop

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    spec = small_bench_spec()
    params = fuse_q40_layer_matmuls(pack_q40_params(
        synth_params(spec, q40=True), allow_nb_major=True))
    step = ft.partial(forward, spec)

    def chain():
        # a FRESH loop per arm: q40_i4_enabled() is read at trace time,
        # and a shared jitted run would serve the first arm's trace to
        # the second (cache hit on identical shapes)
        run = make_decode_loop(step, 12, temperature=0.0, topp=0.9)
        padded = jnp.full((13,), -1, jnp.int32).at[0].set(1)
        coins = jnp.zeros((12,), jnp.float32)
        toks, _ = run(params, init_cache(spec, jnp.float32), padded,
                      jnp.int32(1), coins, jnp.int32(0), jnp.int32(8))
        return np.asarray(toks)

    base = chain()
    monkeypatch.setenv("DLLAMA_Q40_I4", "on")
    # prove the i4 program actually traces: the conversion must appear
    # in the jaxpr of the enabled arm
    from distributed_llama_tpu.runtime.decode import _make_decode_run
    from distributed_llama_tpu.analysis.jaxpr_contracts import walk_fn_eqns

    padded = jnp.full((13,), -1, jnp.int32).at[0].set(1)
    eqns = walk_fn_eqns(
        _make_decode_run(step, 12, 0.0, 0.9), params,
        init_cache(spec, jnp.float32), padded, jnp.int32(1),
        jnp.zeros((12,), jnp.float32), jnp.int32(0), jnp.int32(8))
    assert any(str(e.outvars[0].aval.dtype) == "int4" for e in eqns
               if e.outvars), "i4 conversion absent from the traced chain"
    got = chain()
    np.testing.assert_array_equal(base, got)


def test_i4_packed_carrier_roundtrip(monkeypatch):
    """repack_i4_packed (host u8 carrier, nb-major-only in production —
    the d-major s4 body measured ~6x slower on hardware) -> in-program
    bitcast unpack -> matvec: same integers as the u8 kernel path, and
    the resident carrier is plain uint8 at the SAME byte count."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import (Q40KernelI4PackedNb,
                                                 to_kernel_layout,
                                                 to_kernel_layout_nb)
    from distributed_llama_tpu.ops.pallas_q40 import (q40_matmul,
                                                      repack_i4_packed)

    d, n = 256, 512
    w = _mk(d, n, seed=9)
    # d-major leaves must pass through UNCHANGED (the documented negative)
    kern_d = to_kernel_layout(w)
    assert repack_i4_packed({"w": kern_d})["w"] is kern_d

    kern = to_kernel_layout_nb(w)
    tree = repack_i4_packed({"w": kern})
    leaf = tree["w"]
    assert isinstance(leaf, Q40KernelI4PackedNb)
    assert leaf.qs_p.dtype == np.uint8
    assert leaf.qs_p.nbytes == np.asarray(kern.qs_t).nbytes

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((1, n)).astype(np.float32))
    want = np.asarray(q40_matmul(kern, x))
    got = np.asarray(jax.jit(lambda l, xv: q40_matmul(l, xv))(leaf, x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_i4_packed_decode_chain_parity(monkeypatch):
    """The fused chain over a packed-i4 tree (bitcast prep in-program)
    emits the same tokens as the u8 tree."""
    import functools as ft

    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache
    from distributed_llama_tpu.models.synth import (small_bench_spec,
                                                    synth_params)
    from distributed_llama_tpu.ops.linear import (fuse_q40_layer_matmuls,
                                                  pack_q40_params)
    from distributed_llama_tpu.ops.pallas_q40 import repack_i4_packed
    from distributed_llama_tpu.runtime.decode import make_decode_loop

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    spec = small_bench_spec()
    params = fuse_q40_layer_matmuls(pack_q40_params(
        synth_params(spec, q40=True), allow_nb_major=True))
    step = ft.partial(forward, spec)

    def chain(tree):
        run = make_decode_loop(step, 12, temperature=0.0, topp=0.9)
        padded = jnp.full((13,), -1, jnp.int32).at[0].set(1)
        coins = jnp.zeros((12,), jnp.float32)
        toks, _ = run(tree, init_cache(spec, jnp.float32), padded,
                      jnp.int32(1), coins, jnp.int32(0), jnp.int32(8))
        return np.asarray(toks)

    base = chain(params)
    got = chain(repack_i4_packed(params))
    np.testing.assert_array_equal(base, got)
