"""Model-level tests on a tiny synthetic config (fast on 1 CPU core)."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

TINY = TransformerSpec(dim=64, hidden_dim=160, n_layers=3, n_heads=4,
                       n_kv_heads=2, vocab_size=96, seq_len=32)


def _params(spec, seed=7, scale=0.1):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    p = {"tok_embedding": t(spec.vocab_size, spec.dim),
         "rms_final": 1 + t(spec.dim), "wcls": t(spec.vocab_size, spec.dim),
         "rms_att": 1 + t(spec.n_layers, spec.dim),
         "rms_ffn": 1 + t(spec.n_layers, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        p[name] = t(spec.n_layers, *shape)
    return p


@pytest.fixture(scope="module")
def tiny_model():
    import jax.numpy as jnp

    p = _params(TINY)
    return {k: jnp.asarray(v) for k, v in p.items()}


def test_decode_matches_prefill(tiny_model):
    """T=1 decode chain must equal one chunked-prefill call (cache math)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache

    tokens = np.array([1, 5, 9, 2, 17], dtype=np.int32)

    cache = init_cache(TINY)
    logits_chunk, _ = forward(TINY, tiny_model, cache, jnp.asarray(tokens),
                              jnp.int32(0))

    cache = init_cache(TINY)
    step_logits = []
    for i, tok in enumerate(tokens):
        lg, cache = forward(TINY, tiny_model, cache,
                            jnp.asarray([tok], dtype=jnp.int32), jnp.int32(i))
        step_logits.append(np.asarray(lg[0]))
    np.testing.assert_allclose(np.asarray(logits_chunk), np.stack(step_logits),
                               rtol=0, atol=2e-5)


def test_gqa_kv_cache_shapes(tiny_model):
    from distributed_llama_tpu.models.llama import forward, init_cache

    import jax.numpy as jnp

    cache = init_cache(TINY)
    assert cache.k.shape == (3, 32, 2, 16)  # kvDim=32 < dim=64: GQA
    logits, cache2 = forward(TINY, tiny_model, cache,
                             jnp.asarray([3], dtype=jnp.int32), jnp.int32(0))
    assert logits.shape == (1, TINY.vocab_size)
    # only position 0 written
    assert np.any(np.asarray(cache2.k[:, 0]) != 0)
    assert not np.any(np.asarray(cache2.k[:, 1:]) != 0)


def test_decode_step_jit(tiny_model):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import decode_step, init_cache

    cache = init_cache(TINY)
    logits, cache = decode_step(TINY, tiny_model, cache,
                                jnp.int32(4), jnp.int32(0))
    assert logits.shape == (TINY.vocab_size,)
    logits2, _ = decode_step(TINY, tiny_model, cache, jnp.int32(7),
                             jnp.int32(1))
    assert not np.allclose(np.asarray(logits), np.asarray(logits2))


def test_q80_buffer_mode_close_to_f32(tiny_model):
    """Q80 fake-quant at the sync points stays within quantization tolerance."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache

    spec80 = TransformerSpec(**{**TINY.__dict__,
                                "buffer_float_type": FloatType.Q80})
    tokens = jnp.asarray([1, 5, 9], dtype=jnp.int32)
    lg32, _ = forward(TINY, tiny_model, init_cache(TINY), tokens, jnp.int32(0))
    lg80, _ = forward(spec80, tiny_model, init_cache(spec80), tokens,
                      jnp.int32(0))
    diff = np.abs(np.asarray(lg32) - np.asarray(lg80)).max()
    assert 0 < diff < 0.05  # quantization changes values, but not much


def test_q40_weights_forward(tmp_path):
    """End-to-end: write a Q40 .bin, load it, run the model, compare to the
    same-weights F32 run within Q40 tolerance."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import load_model, write_model
    from distributed_llama_tpu.models.llama import forward, init_cache, params_to_device

    p = _params(TINY)
    tensors = {**p}
    spec_q = TransformerSpec(**{**TINY.__dict__,
                                "weights_float_type": FloatType.Q40})
    path = str(tmp_path / "m.bin")
    write_model(path, spec_q, tensors)
    _, params_np = load_model(path, spec_q)
    params_q = params_to_device(params_np)

    tokens = jnp.asarray([2, 11], dtype=jnp.int32)
    lg_q, _ = forward(spec_q, params_q, init_cache(spec_q), tokens, jnp.int32(0))

    # exact check: Q40 forward == forward over explicitly dequantized weights
    from distributed_llama_tpu.io.loader import Q40Weight
    from distributed_llama_tpu.ops.quants import dequantize_q40

    p_deq = {k: (dequantize_q40(v.qs, v.d16) if isinstance(v, Q40Weight) else v)
             for k, v in params_np.items()}
    lg_deq, _ = forward(TINY, params_to_device(p_deq), init_cache(TINY), tokens,
                        jnp.int32(0))
    np.testing.assert_allclose(np.asarray(lg_q), np.asarray(lg_deq),
                               rtol=0, atol=1e-5)

    # loose sanity vs the unquantized model: same ballpark, not identical
    lg_f, _ = forward(TINY, params_to_device(p), init_cache(TINY), tokens,
                      jnp.int32(0))
    diff = np.abs(np.asarray(lg_q) - np.asarray(lg_f)).max()
    assert 0 < diff < 5.0


def test_rope_matches_scalar_reference():
    """rope_rotate vs a direct transcription of the reference's scalar loop
    (transformer-tasks.cpp:228-242), on a GQA shape where kvDim < dim."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import rope_rotate

    head_size = 16
    dim = 64
    rng = np.random.default_rng(3)
    q = rng.standard_normal((1, dim)).astype(np.float32)
    pos = 5

    expected = q[0].copy()
    for i in range(0, dim, 2):
        head_dim = i % head_size
        freq = 1.0 / (10000.0 ** (head_dim / head_size))
        val = pos * freq
        fcr, fci = np.cos(val), np.sin(val)
        v0, v1 = expected[i], expected[i + 1]
        expected[i] = v0 * fcr - v1 * fci
        expected[i + 1] = v0 * fci + v1 * fcr

    got = np.asarray(rope_rotate(jnp.asarray(q),
                                 jnp.asarray([pos], dtype=jnp.int32),
                                 head_size))[0]
    np.testing.assert_allclose(got, expected, rtol=0, atol=1e-5)


def test_bf16_kv_cache_close_to_f32(tiny_model):
    """bf16 KV cache (memory/bandwidth mode) stays within bf16 rounding of
    the f32 parity path across a short multi-token decode."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)

    p = params_to_device(tiny_model)
    toks = [5, 9, 2, 40]
    lg32 = lgbf = None
    c32 = init_cache(TINY)
    cbf = init_cache(TINY, jnp.bfloat16)
    assert cbf.k.dtype == jnp.bfloat16
    for pos, t in enumerate(toks):
        tok = jnp.asarray([t], jnp.int32)
        lg32, c32 = forward(TINY, p, c32, tok, jnp.int32(pos))
        lgbf, cbf = forward(TINY, p, cbf, tok, jnp.int32(pos))
        assert cbf.k.dtype == jnp.bfloat16  # dtype survives the update
    import numpy as np

    diff = np.abs(np.asarray(lg32) - np.asarray(lgbf)).max()
    assert diff < 0.05  # bf16 has ~3 decimal digits; logits are O(1)
