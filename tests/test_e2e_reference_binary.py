"""Cross-binary end-to-end parity against the COMPILED reference.

The one claim the piecewise parity gates (tokenizer, sampler, golden block,
loader byte-exactness) cannot make individually: the whole composed system —
convert -> load -> encode -> decode-loop -> detokenize — agrees with the
reference *executable* on the same model file, same tokenizer file, same
prompt (VERDICT r3 #1).

Two layers:

* ``test_token_stream_matches_reference_binary`` builds the reference's own
  ``main`` from /root/reference/src (unmodified, out-of-tree) and runs
  ``main inference --steps N --temperature 0`` (main.cpp:38-63,
  tokenizer.cpp:321-394) against this repo's CLI on the same fixture files,
  asserting the identical decoded text stream and token count.
* ``test_per_step_logits_match_reference`` links the reference objects under
  tests/e2e/ref_probe.cpp (our driver; dumps raw per-step logits + argmax
  ids) and compares this repo's Engine logits step by step.

Tolerance note (documented per VERDICT r3 #1): both sides compute in f32,
but XLA:CPU reduces matmuls in vectorized/tiled order while the reference
accumulates serially (funcs.cpp matmulF32), so individual logits differ by
f32 associativity noise. Measured on this fixture: max |diff| 1.2e-6 over
12 steps; the gate is 1e-4 absolute plus exact argmax-id equality every
step (the quantity that decides the token stream).

The model fixture is written by THIS repo's writers (io/loader.write_model,
io/tokenizer.write_tokenizer) and read by the reference's loader — the
byte-format contract (transformer.cpp:280-352, tokenizer.cpp:43-54) is part
of what's under test.
"""

import ast
import glob
import os
import shutil
import subprocess

import numpy as np
import pytest

from distributed_llama_tpu.io.loader import load_model, write_model
from distributed_llama_tpu.io.tokenizer import Tokenizer, write_tokenizer
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

REF_SRC = "/root/reference/src"
STEPS = 12
PROMPT = "hi hix hi"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF_SRC) or shutil.which("g++") is None,
    reason="reference sources or g++ unavailable")

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=300, seq_len=32,
                       weights_float_type=FloatType.F32)


@pytest.fixture(scope="module")
def ref_binaries(tmp_path_factory):
    """Compile the unmodified reference + our logit probe, out-of-tree."""
    d = tmp_path_factory.mktemp("refbuild")
    srcs = sorted(glob.glob(os.path.join(REF_SRC, "*.cpp")))
    core = [s for s in srcs
            if not s.endswith("-test.cpp")
            and os.path.basename(s) != "main.cpp"]
    main = os.path.join(REF_SRC, "main.cpp")
    probe_src = os.path.join(os.path.dirname(__file__), "e2e",
                             "ref_probe.cpp")
    ref_main = str(d / "ref_main")
    ref_probe = str(d / "ref_probe")
    for out, extra in ((ref_main, [main]), (ref_probe, [probe_src])):
        subprocess.run(
            ["g++", "-std=c++11", "-O2", "-I", REF_SRC, *core, *extra,
             "-lpthread", "-o", out],
            check=True, capture_output=True, text=True)
    return ref_main, ref_probe


def _make_fixture(spec, d):
    """Tiny seeded F32 model + tokenizer, written by this repo's writers."""
    rng = np.random.default_rng(11)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.08).astype(np.float32)

    tensors = {"tok_embedding": t(spec.vocab_size, spec.dim),
               "rms_att": 1 + 0.1 * t(spec.n_layers, spec.dim),
               "rms_ffn": 1 + 0.1 * t(spec.n_layers, spec.dim),
               "rms_final": 1 + 0.1 * t(spec.dim),
               "wcls": t(spec.vocab_size, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        tensors[name] = t(spec.n_layers, *shape)
    model = str(d / "model.bin")
    write_model(model, spec, tensors)

    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    pieces += [b" ", b"h", b"i", b"hi", b" hi", b"x", b" h"]
    while len(pieces) < spec.vocab_size:
        pieces.append(f"tok{len(pieces)}".encode())
    scores = [0.0] * len(pieces)
    scores[pieces.index(b"hi")] = -0.5
    scores[pieces.index(b" hi")] = -0.4
    scores[pieces.index(b" h")] = -0.9
    tok = str(d / "tok.bin")
    write_tokenizer(tok, pieces, scores)
    return model, tok


@pytest.fixture(scope="module")
def fixture_files(tmp_path_factory):
    return _make_fixture(SPEC, tmp_path_factory.mktemp("fixture"))


def _run_ref_main(ref_main, model, tok):
    r = subprocess.run(
        [ref_main, "inference", "--model", model, "--tokenizer", tok,
         "--prompt", PROMPT, "--steps", str(STEPS), "--temperature", "0",
         "--nthreads", "1", "--weights-float-type", "f32",
         "--buffer-float-type", "f32"],
        check=True, capture_output=True, text=True, timeout=120)
    return r.stdout


def _printable(s: str) -> str:
    """The reference's safePrintf drops 'unsafe' bytes (tokenizer.cpp:
    safePrintf) while this repo's CLI prints a repr with U+FFFD for raw
    byte-fallback tokens — normalize both to the printable stream. Exact
    token-level agreement is asserted separately by the probe test."""
    return "".join(c for c in s if c.isprintable() or c.isspace())


def _parse_ref_pieces(stdout: str) -> tuple[str, int, int]:
    pieces = []
    n_tokens = None
    for line in stdout.splitlines():
        if line.startswith("🔶"):
            # "🔶 G .. ms I .. ms T .. ms S .. kB R .. kB <piece>"
            pieces.append(line.split(" kB ", 2)[2])
        elif line.startswith("Generated tokens:"):
            n_tokens = int(line.split(":")[1])
    assert n_tokens is not None, stdout
    return _printable("".join(pieces)), n_tokens, len(pieces)


def _parse_our_pieces(stdout: str) -> tuple[str, int, int]:
    pieces = []
    n_tokens = None
    for line in stdout.splitlines():
        if line.startswith("🔶"):
            pieces.append(ast.literal_eval(line.split(" kB ", 2)[2])
                          .replace("�", ""))
        elif line.startswith("Generated tokens:"):
            n_tokens = int(line.split(":")[1])
    assert n_tokens is not None, stdout
    return _printable("".join(pieces)), n_tokens, len(pieces)


def test_token_stream_matches_reference_binary(ref_binaries, fixture_files,
                                               capsys):
    from distributed_llama_tpu.frontend.cli import main

    ref_main, _ = ref_binaries
    model, tok = fixture_files
    ref_text, ref_n, ref_lines = _parse_ref_pieces(
        _run_ref_main(ref_main, model, tok))

    rc = main(["inference", "--model", model, "--tokenizer", tok,
               "--prompt", PROMPT, "--steps", str(STEPS),
               "--temperature", "0", "--tp", "1",
               "--weights-float-type", "f32", "--buffer-float-type", "f32",
               "--seed", "1"])
    assert rc == 0
    our_text, our_n, our_lines = _parse_our_pieces(capsys.readouterr().out)
    assert our_n == ref_n
    assert our_lines == ref_lines
    assert our_text == ref_text
    # the fixture must actually generate past the prompt, or this test
    # proves nothing about the sampled stream
    assert ref_n > 5


def _distributed_parity(ref_main, model, tok, n_workers, capsys):
    """Run the reference root + n_workers worker PROCESSES over localhost
    TCP (its actual socket protocol, weight scatter included —
    main.cpp:65-77, transformer.cpp:354-380) against this repo's
    tp=(n_workers+1) mesh program; decoded stream and token count must
    agree."""
    import socket as socketlib
    import time as timelib

    from distributed_llama_tpu.frontend.cli import main

    def free_port():
        with socketlib.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def spawn(port):
        return subprocess.Popen(
            [ref_main, "worker", "--port", str(port), "--nthreads", "1"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    ports = [free_port() for _ in range(n_workers)]
    workers = [spawn(p) for p in ports]
    try:
        # a fixed readiness sleep races on loaded hosts, and a probe
        # connection would be CONSUMED as a worker's single accept() — so
        # retry the root itself. A PARTIAL connect (some workers up, one
        # not yet listening) consumes the up workers' accept and they die
        # when the root exits (socket.cpp:58-61 exit on closed socket), so
        # each retry respawns dead workers on fresh ports.
        deadline = timelib.time() + 30
        while True:
            r = subprocess.run(
                [ref_main, "inference", "--model", model,
                 "--tokenizer", tok, "--prompt", PROMPT,
                 "--steps", str(STEPS), "--temperature", "0",
                 "--nthreads", "1", "--weights-float-type", "f32",
                 "--buffer-float-type", "f32", "--workers",
                 *[f"127.0.0.1:{p}" for p in ports]],
                capture_output=True, text=True, timeout=120)
            if r.returncode == 0:
                break
            assert timelib.time() < deadline, (
                f"root never connected: {r.stdout}\n{r.stderr}")
            timelib.sleep(0.25)
            for i, w in enumerate(workers):
                if w.poll() is not None:
                    w.wait()
                    ports[i] = free_port()
                    workers[i] = spawn(ports[i])
    finally:
        for w in workers:
            w.kill()
            w.wait()
    ref_text, ref_n, ref_lines = _parse_ref_pieces(r.stdout)

    rc = main(["inference", "--model", model, "--tokenizer", tok,
               "--prompt", PROMPT, "--steps", str(STEPS),
               "--temperature", "0", "--tp", str(n_workers + 1),
               "--weights-float-type", "f32", "--buffer-float-type", "f32",
               "--seed", "1"])
    assert rc == 0
    our_text, our_n, our_lines = _parse_our_pieces(capsys.readouterr().out)
    assert (our_n, our_lines, our_text) == (ref_n, ref_lines, ref_text)


def test_distributed_stream_matches_reference_2node(ref_binaries,
                                                    fixture_files, capsys):
    ref_main, _ = ref_binaries
    model, tok = fixture_files
    _distributed_parity(ref_main, model, tok, n_workers=1, capsys=capsys)


def test_distributed_stream_matches_reference_4node(ref_binaries,
                                                    tmp_path, capsys):
    """tp=4: the reference's published sweet-spot device count
    (README.md:46-47). Needs 8 query / 4 kv heads so every rank holds a
    whole head (GQA kv_mul=2 — the deep-GQA slicing is part of what's
    under test on our side)."""
    spec4 = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=8,
                            n_kv_heads=4, vocab_size=300, seq_len=32,
                            weights_float_type=FloatType.F32)
    ref_main, _ = ref_binaries
    model, tok = _make_fixture(spec4, tmp_path)
    _distributed_parity(ref_main, model, tok, n_workers=3, capsys=capsys)


def test_per_step_logits_match_reference(ref_binaries, fixture_files,
                                         tmp_path):
    from distributed_llama_tpu.runtime.generate import Engine

    _, ref_probe = ref_binaries
    model, tok = fixture_files
    logits_path = str(tmp_path / "logits.bin")
    r = subprocess.run(
        [ref_probe, model, tok, PROMPT, str(STEPS), logits_path],
        check=True, capture_output=True, text=True, timeout=120)
    ref_steps = []  # (pos, token, next)
    for line in r.stdout.splitlines():
        if line.startswith("TOK "):
            _, pos, token, nxt = line.split()
            ref_steps.append((int(pos), int(token), int(nxt)))
    assert len(ref_steps) == STEPS
    ref_logits = np.fromfile(logits_path, dtype=np.float32).reshape(
        STEPS, SPEC.vocab_size)

    spec, params = load_model(model, weights_float_type=FloatType.F32,
                              buffer_float_type=FloatType.F32)
    engine = Engine(spec, params)
    tokenizer = Tokenizer(tok, spec.vocab_size)
    prompt_tokens = tokenizer.encode(PROMPT, bos=True, eos=False)
    # the encoders must agree before the forward even runs
    assert prompt_tokens[0] == ref_steps[0][1]

    token = prompt_tokens[0]
    max_diff = 0.0
    for pos in range(STEPS):
        logits = engine.infer(token, pos)
        max_diff = max(max_diff, float(np.max(np.abs(
            logits - ref_logits[pos]))))
        if pos < len(prompt_tokens) - 1:
            nxt = prompt_tokens[pos + 1]
        else:
            nxt = int(np.argmax(logits))
        assert (pos, token, nxt) == ref_steps[pos], \
            f"step {pos}: ours {(pos, token, nxt)} ref {ref_steps[pos]}" \
            f" (max logit diff so far {max_diff})"
        token = nxt
    # f32-associativity tolerance, see module docstring
    assert max_diff < 1e-4, max_diff
