"""Batched decode: B lockstep sequences must match B single-sequence runs."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


@pytest.fixture(scope="module")
def params_dev():
    from distributed_llama_tpu.models.llama import params_to_device

    return params_to_device(synth_params(SPEC, q40=False, seed=4, scale=0.3))


def test_forward_batch_matches_singles(params_dev):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, forward_batch,
                                                    init_cache,
                                                    init_cache_batch)

    B = 3
    rows = {0: [7], 1: [17, 3], 2: [40, 88]}  # per-row token history
    pos_hist = 2  # shared position clock: rows already ran pos 0..1
    rows[0].append(11)  # make all histories length 2 (lockstep contract)

    singles = []
    caches = []
    tokens_now = jnp.asarray([5, 9, 77], dtype=jnp.int32)
    for b in range(B):
        c = init_cache(SPEC)
        for p, t in enumerate(rows[b]):
            _, c = forward(SPEC, params_dev, c,
                           jnp.asarray([t], jnp.int32), jnp.int32(p))
        caches.append(c)
        lg, c2 = forward(SPEC, params_dev, c, tokens_now[b][None],
                         jnp.int32(pos_hist))
        singles.append((np.asarray(lg[0]), c2))

    cache_b = init_cache_batch(SPEC, B)
    cache_b = cache_b._replace(
        k=jnp.stack([c.k for c in caches], axis=1),
        v=jnp.stack([c.v for c in caches], axis=1))
    lg_b, cache_b2 = forward_batch(SPEC, params_dev, cache_b, tokens_now,
                                   jnp.int32(pos_hist))

    for b in range(B):
        np.testing.assert_allclose(np.asarray(lg_b[b]), singles[b][0],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(cache_b2.k[:, b]),
                                   np.asarray(singles[b][1].k),
                                   rtol=1e-5, atol=1e-5)


def test_batch_decode_loop_matches_single_loop(params_dev):
    import functools

    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    init_cache_batch)
    from distributed_llama_tpu.runtime.decode import (make_batch_decode_loop,
                                                      make_decode_loop)

    steps = 8
    B = 2
    prompts = [[1, 5, 9], [1, 22]]  # ragged: row 1 starts sampling earlier

    single_out = []
    step = functools.partial(forward, SPEC)
    run1 = make_decode_loop(step, steps, temperature=0.0, topp=0.9)
    for p in prompts:
        padded = np.full((steps + 1,), -1, dtype=np.int32)
        padded[:len(p)] = p
        toks, _ = run1(params_dev, init_cache(SPEC), jnp.asarray(padded),
                       jnp.int32(p[0]), jnp.zeros((steps,), jnp.float32),
                       jnp.int32(0), jnp.int32(steps))
        single_out.append(np.asarray(toks))

    runb = make_batch_decode_loop(SPEC, steps, temperature=0.0, topp=0.9)
    padded = np.full((B, steps + 1), -1, dtype=np.int32)
    for b, p in enumerate(prompts):
        padded[b, :len(p)] = p
    toks_b, _ = runb(params_dev, init_cache_batch(SPEC, B),
                     jnp.asarray(padded),
                     jnp.asarray([p[0] for p in prompts], jnp.int32),
                     jnp.zeros((B, steps), jnp.float32))
    toks_b = np.asarray(toks_b)
    for b in range(B):
        np.testing.assert_array_equal(toks_b[b], single_out[b])


def test_batch_loop_rejects_steps_past_seq_len(params_dev):
    from distributed_llama_tpu.runtime.decode import make_batch_decode_loop

    with pytest.raises(ValueError, match="seq_len"):
        make_batch_decode_loop(SPEC, SPEC.seq_len + 1, 0.0, 0.9)
