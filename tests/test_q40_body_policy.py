"""Q40 decode-body policy (ISSUE 3 satellite): the bench's A/B-winning
i4-plane + nb-major combo must reach plain `inference` runs through ONE
policy function, with DLLAMA_Q40_BODY as the explicit override and loud
reasons either way. Decision logic only — the kernels themselves are
pinned by tests/test_pallas_q40.py."""

from __future__ import annotations

import pytest

from distributed_llama_tpu.models.synth import (llama2_7b_spec,
                                                llama2_13b_spec)
from distributed_llama_tpu.ops.linear import (apply_q40_body_policy,
                                              q40_body_policy)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("DLLAMA_Q40_BODY", "DLLAMA_Q40_I4", "DLLAMA_NB_MAJOR",
                "DLLAMA_Q40_BODY_MAX_GB", "DLLAMA_Q40_KERNEL"):
        monkeypatch.delenv(var, raising=False)
    yield


def test_auto_picks_i4_nb_for_7b_on_pallas(monkeypatch):
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    policy, reason = q40_body_policy(llama2_7b_spec())
    assert policy == "i4-nb"
    assert "auto" in reason


def test_auto_declines_13b_on_memory_headroom(monkeypatch):
    # the 13B i4 conversion OOMed a 16 GB chip (BASELINE.md r5): auto must
    # keep d-major there, and say why
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    policy, reason = q40_body_policy(llama2_13b_spec())
    assert policy == "d-major"
    assert "headroom" in reason
    # ... but a raised gate flips it (the knob the bench's tp2/tp4 rank
    # rows effectively use at their smaller band sizes)
    monkeypatch.setenv("DLLAMA_Q40_BODY_MAX_GB", "12")
    policy, _ = q40_body_policy(llama2_13b_spec())
    assert policy == "i4-nb"


def test_auto_declines_off_pallas():
    # CPU / xla mode: layouts are moot, keep the stock picks
    policy, reason = q40_body_policy(llama2_7b_spec())
    assert policy == "d-major"
    assert "Pallas" in reason or "XLA" in reason


def test_explicit_env_always_wins(monkeypatch):
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    monkeypatch.setenv("DLLAMA_Q40_I4", "off")
    # the label reports what the env actually engages — never a policy
    # nobody chose (a mislabel would defeat the comparability note)
    policy, reason = q40_body_policy(llama2_7b_spec())
    assert policy == "env(i4=off, nb-major=auto)" and "respected" in reason

    # direct env knobs beat DLLAMA_Q40_BODY too (nothing unsets user env)
    monkeypatch.setenv("DLLAMA_Q40_BODY", "i4-nb")
    policy, reason = q40_body_policy(llama2_7b_spec())
    assert policy.startswith("env(") and "respected" in reason

    # the exact winning combo set by hand reports as itself
    monkeypatch.setenv("DLLAMA_Q40_I4", "on")
    monkeypatch.setenv("DLLAMA_NB_MAJOR", "force")
    assert q40_body_policy(llama2_7b_spec())[0] == "i4-nb"

    monkeypatch.delenv("DLLAMA_Q40_I4")
    monkeypatch.delenv("DLLAMA_NB_MAJOR")
    policy, reason = q40_body_policy(llama2_7b_spec())
    assert policy == "i4-nb" and "explicit DLLAMA_Q40_BODY" in reason

    monkeypatch.setenv("DLLAMA_Q40_BODY", "nope")
    with pytest.raises(ValueError, match="DLLAMA_Q40_BODY"):
        q40_body_policy(llama2_7b_spec())


def test_apply_sets_env_knobs_and_notes(monkeypatch, capsys):
    import os

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    assert apply_q40_body_policy(llama2_7b_spec()) == "i4-nb"
    assert os.environ["DLLAMA_NB_MAJOR"] == "force"
    assert os.environ["DLLAMA_Q40_I4"] == "on"
    assert "Q40 body policy: i4-nb" in capsys.readouterr().err


def test_apply_never_overrides_explicit_env(monkeypatch, capsys):
    import os

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    monkeypatch.setenv("DLLAMA_Q40_BODY", "i4-nb")  # forced policy...
    monkeypatch.setenv("DLLAMA_Q40_I4", "off")      # ...but explicit knob
    apply_q40_body_policy(llama2_7b_spec())
    assert os.environ["DLLAMA_Q40_I4"] == "off"     # user env untouched
    # an env-labeled outcome sets NOTHING (the user's partial config is
    # not silently completed) and the note says what actually engages
    assert "DLLAMA_NB_MAJOR" not in os.environ
    assert "Q40 body policy: env(i4=off" in capsys.readouterr().err
