"""Back-compat shim: the jaxpr introspection helpers moved into the
package as ``distributed_llama_tpu.analysis.jaxpr_contracts`` (the dlint
contract head uses them at CLI time, not just under pytest). Import from
there; this shim keeps old `from jaxpr_utils import ...` call sites
working."""

from distributed_llama_tpu.analysis.jaxpr_contracts import (  # noqa: F401
    walk_eqns, walk_fn_eqns)
