"""Shared jaxpr introspection for structure-pinning tests.

The recursion duck-types on JAX internals (eqn params that hold Jaxpr /
ClosedJaxpr values), which can break quietly on a JAX upgrade — keeping ONE
copy means a breakage shows up everywhere at once instead of leaving a
vacuously-passing twin behind. The self-check below turns "yields nothing"
into a loud failure.
"""

from __future__ import annotations


def walk_eqns(jaxpr):
    """Yield every eqn in a jaxpr, recursing into sub-jaxprs (shard_map,
    scan, while, cond bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if hasattr(v, "eqns"):
                yield from walk_eqns(v)
            elif inner is not None and hasattr(inner, "eqns"):
                yield from walk_eqns(inner)


def walk_fn_eqns(fn, *args):
    """walk_eqns over jax.make_jaxpr(fn)(*args); asserts non-empty so an
    internal-API drift can't silently yield zero eqns."""
    import jax

    eqns = list(walk_eqns(jax.make_jaxpr(fn)(*args).jaxpr))
    assert eqns, "jaxpr walk yielded nothing — JAX internals changed?"
    return eqns
