"""tools/it_split.py: profiler-derived I/T attribution (VERDICT r1 #5).

The reference publishes a per-token inference/transfer split from task-type
wall timing (utils.cpp:101-109, printed at tokenizer.cpp:381); our equivalent
buckets profiled device-op time into compute vs collectives. Gate: a real
tensor-parallel decode traced on the 8-virtual-device CPU mesh must yield a
split with BOTH buckets populated and the four per-layer all_gathers (+
logits gather) visible as collective time.
"""

import io

import pytest

from distributed_llama_tpu.utils import it_split


@pytest.fixture(scope="module")
def tp_trace(tmp_path_factory):
    """Trace a few tp=2 decode steps of the tiny model on the CPU mesh."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=128, seq_len=16)
    params = shard_params(synth_params(spec, q40=False, seed=5, scale=0.2),
                          make_mesh(tp=2))
    mesh = make_mesh(tp=2)
    fwd = make_sharded_forward(spec, mesh)
    cache = shard_cache(init_cache(spec), mesh)
    tok = jnp.asarray([7], jnp.int32)
    logits, cache = fwd(params, cache, tok, jnp.int32(0))  # compile first
    logits.block_until_ready()
    trace_dir = str(tmp_path_factory.mktemp("trace"))
    with jax.profiler.trace(trace_dir):
        for pos in range(1, 4):
            logits, cache = fwd(params, cache, tok, jnp.int32(pos))
        logits.block_until_ready()
    return trace_dir


def test_split_buckets_compute_and_collectives(tp_trace):
    splits = it_split.parse_trace(tp_trace)
    assert splits  # at least one device's op line parsed
    total_i = sum(s.inference_ns for s in splits.values())
    total_t = sum(s.transfer_ns for s in splits.values())
    assert total_i > 0 and total_t > 0
    ops = set()
    for s in splits.values():
        ops |= set(s.ops)
    assert any("all_gather" in o or "all-gather" in o for o in ops)
    # compute ops must NOT be tagged transfer: the matmuls of the layer body
    assert any(("dot" in o or "fusion" in o or "matmul" in o) for o in ops)


def test_summarize_prints_reference_shape(tp_trace):
    splits = it_split.parse_trace(tp_trace)
    buf = io.StringIO()
    i_ms, t_ms = it_split.summarize(splits, tokens=3, top=5, out=buf)
    text = buf.getvalue()
    assert "🔶 I" in text and "T" in text and "ms/token" in text
    assert i_ms > 0 and t_ms > 0


def test_classifier_rules():
    """Collective vs compute classification on representative HLO names."""
    coll = ["all_gather.3", "all-gather-start.1", "all-reduce.7",
            "reduce-scatter.2", "collective-permute-done.5", "all-to-all.1"]
    comp = ["dot_general.3", "fusion.12", "tpu_custom_call",
            "wrapped_reduce-window", "scatter.2", "dynamic-update-slice.9"]
    for n in coll:
        assert it_split._COLLECTIVE_RE.search(n), n
    for n in comp:
        assert not it_split._COLLECTIVE_RE.search(n), n


def test_missing_trace_errors(tmp_path):
    with pytest.raises(FileNotFoundError, match="profile"):
        it_split.find_xplane(str(tmp_path))


def test_hlo_instruction_names_extracted():
    """Real-TPU 'XLA Ops' lines carry full HLO text; the parser must
    extract the instruction name and classify on it."""
    m = it_split._HLO_RE.match(
        "%all-gather.7 = f32[4096]{0} all-gather(f32[512]{0} %p), dims={0}")
    assert m and m.group(1) == "all-gather.7"
    assert it_split._COLLECTIVE_RE.search(m.group(1))
    m2 = it_split._HLO_RE.match(
        "%convolution_reduce_fusion = f32[]{:T(128)} fusion(...)")
    assert m2 and not it_split._COLLECTIVE_RE.search(m2.group(1))


def test_op_name_filter_underscore_rules():
    """Single-underscore Pallas custom calls (jit fn names) are ops; dunder
    runtime helpers are not."""
    assert it_split._OP_RE.match("_q40_matmul_stacked.48")
    assert it_split._OP_RE.match("_q40_matvec_nb_stacked")
    assert not it_split._OP_RE.match("__xla_thunk_helper")
    assert not it_split._OP_RE.match("PjitFunction(f)")
