"""Trace-context identity layer + span/log trace plumbing (ISSUE 15).

Covers obs/tracectx.py (the one id producer, header round-trips,
child/link derivation, malformed-header refusal), the SpanTracer ring-
overflow accounting (dllama_spans_dropped_total + the ``dropped`` export
fields — the silent-truncation satellite), trace filtering of exports,
and the --log-json trace stamping satellite."""

import json

import pytest

from distributed_llama_tpu.obs import tracectx
from distributed_llama_tpu.obs.spans import SpanTracer, validate_chrome_trace


# ----------------------------------------------------------- id producer


def test_ids_are_hex_and_unique():
    tids = {tracectx.new_trace_id() for _ in range(200)}
    sids = {tracectx.new_span_id() for _ in range(200)}
    assert len(tids) == 200 and len(sids) == 200
    assert all(len(t) == 32 and int(t, 16) >= 0 for t in tids)
    assert all(len(s) == 16 and int(s, 16) >= 0 for s in sids)


def test_seeded_ids_reproduce_and_reset():
    tracectx.seed_ids(42)
    try:
        a = tracectx.new_trace_id()
        tracectx.seed_ids(42)
        b = tracectx.new_trace_id()
        assert a == b
    finally:
        tracectx.seed_ids(None)
    # back on urandom: practically never equal
    assert tracectx.new_trace_id() != tracectx.new_trace_id()


def test_mint_child_and_links():
    root = tracectx.mint()
    assert root.parent_id is None and root.link is None
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    linked = root.child(link=tracectx.LINK_RECOVERS)
    assert linked.link == "recovers"
    with pytest.raises(ValueError, match="link"):
        root.child(link="teleport")


def test_header_roundtrip_and_from_header():
    root = tracectx.mint()
    hdr = root.to_header()
    assert hdr == f"00-{root.trace_id}-{root.span_id}-01"
    back = tracectx.parse_header(hdr)
    assert (back.trace_id, back.span_id) == (root.trace_id, root.span_id)
    cont = tracectx.from_header(hdr, link=tracectx.LINK_HANDOFF)
    assert cont.trace_id == root.trace_id
    assert cont.parent_id == root.span_id
    assert cont.link == "handoff"


@pytest.mark.parametrize("bad", [
    "", "nonsense", "01-" + "a" * 32 + "-" + "b" * 16 + "-01",
    "00-short-span-01", "00-" + "g" * 32 + "-" + "b" * 16 + "-01",
    None, 7])
def test_malformed_headers_refuse(bad):
    with pytest.raises(ValueError):
        tracectx.parse_header(bad)


def test_span_fields_shapes():
    root = tracectx.mint()
    assert tracectx.span_fields(None) == {}
    assert tracectx.span_fields(root) == {"trace_id": root.trace_id,
                                          "span_id": root.span_id}
    child = root.child(link="handoff")
    fields = tracectx.span_fields(child)
    assert fields["parent_span_id"] == root.span_id
    assert fields["link"] == "handoff"


# ------------------------------------------------- span-ring overflow fix


def test_span_ring_overflow_counted_and_exported():
    """The silent-truncation satellite: an overflowing ring counts every
    eviction, fires on_drop (the metric hook), and both exports carry
    the count."""
    drops = []
    tr = SpanTracer(capacity=4, on_drop=lambda: drops.append(1))
    for i in range(10):
        tr.add(f"s{i}", "phase", float(i), 0.001)
    assert tr.dropped == 6 and len(drops) == 6
    doc = tr.export_chrome()
    validate_chrome_trace(doc)
    assert doc["dropped"] == 6
    lines = tr.export_ndjson().strip().splitlines()
    meta = json.loads(lines[-1])
    assert meta["span"] == "_meta" and meta["dropped"] == 6
    assert len(lines) == 5  # 4 spans + the meta record
    # an un-overflowed tracer exports no meta line and dropped == 0
    quiet = SpanTracer(capacity=4)
    quiet.add("a", "phase", 0.0, 0.001)
    assert quiet.export_chrome()["dropped"] == 0
    assert all(json.loads(ln)["span"] != "_meta"
               for ln in quiet.export_ndjson().strip().splitlines())


def test_engine_overflow_moves_spans_dropped_metric():
    import numpy as np  # noqa: F401  (jax import below needs the env)

    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.obs.metrics import Registry
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=128, seq_len=16)
    reg = Registry()
    eng = ContinuousEngine(spec, synth_params(spec, q40=False, seed=4,
                                              scale=0.3),
                           slots=1, temperature=0.0, topp=0.9, seed=5,
                           metrics=reg)
    assert "dllama_spans_dropped_total 0" in reg.expose()
    # shrink the ring so the run overflows it
    eng._spans._spans = type(eng._spans._spans)(maxlen=2)
    eng.run([[1, 5, 9], [1, 7]], steps=6)
    counter = reg.get("dllama_spans_dropped_total")
    assert counter.value == eng._spans.dropped > 0


def test_span_trace_filter():
    a, b = tracectx.mint(), tracectx.mint()
    tr = SpanTracer()
    tr.add("request", "request", 0.0, 0.1, **tracectx.span_fields(a))
    tr.add("request", "request", 0.2, 0.1, **tracectx.span_fields(b))
    tr.add("step", "decode", 0.0, 0.05)  # no trace: engine-wide span
    assert len(tr.snapshot()) == 3
    only_a = tr.snapshot(trace_id=a.trace_id)
    assert len(only_a) == 1 and only_a[0].meta["trace_id"] == a.trace_id
    doc = tr.export_chrome(trace_id=b.trace_id)
    assert len(doc["traceEvents"]) == 1
    assert doc["traceEvents"][0]["args"]["trace_id"] == b.trace_id
    nd = [json.loads(ln) for ln in
          tr.export_ndjson(trace_id=a.trace_id).strip().splitlines()]
    assert [r["trace_id"] for r in nd] == [a.trace_id]


# ------------------------------------------------- --log-json trace ids


def test_log_event_stamps_trace_ids(monkeypatch, capsys):
    """The logs-join-traces satellite: a --log-json record emitted with a
    TraceContext carries trace_id/span_id from the SAME producer the
    spans use."""
    from distributed_llama_tpu.obs.log import log_event

    monkeypatch.setenv("DLLAMA_LOG_JSON", "1")
    ctx = tracectx.mint().child(link="handoff")
    log_event("disagg.handoff_shipped", None, trace=ctx, pages=2)
    rec = json.loads(capsys.readouterr().out.strip())
    assert rec["event"] == "disagg.handoff_shipped"
    assert rec["trace_id"] == ctx.trace_id
    assert rec["span_id"] == ctx.span_id
    assert rec["parent_span_id"] == ctx.parent_id
    assert rec["link"] == "handoff" and rec["pages"] == 2
    # without a context the record carries no trace fields
    log_event("plain.event", None, n=1)
    rec2 = json.loads(capsys.readouterr().out.strip())
    assert "trace_id" not in rec2
    # text mode ignores the context entirely
    monkeypatch.setenv("DLLAMA_LOG_JSON", "0")
    log_event("x", "human line", trace=ctx)
    assert capsys.readouterr().out == "human line\n"
