"""Interpret-mode parity tests for the flash-decode attention kernel."""

import numpy as np
import pytest


@pytest.mark.parametrize("kv_mul,pos", [(1, 0), (1, 5), (1, 31), (2, 9),
                                        (4, 17), (8, 9)])
def test_decode_attention_matches_core(kv_mul, pos):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (attention_core,
                                                    causal_cache_mask)
    from distributed_llama_tpu.ops.pallas_attention import decode_attention

    L, S, n_kv, hs = 3, 32, 4, 128
    n_q = n_kv * kv_mul
    layer = 1
    rng = np.random.default_rng(pos * 7 + kv_mul)
    k_all = jnp.asarray(rng.normal(size=(L, S, n_kv, hs)).astype(np.float32))
    v_all = jnp.asarray(rng.normal(size=(L, S, n_kv, hs)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(n_q, hs)).astype(np.float32))

    want = attention_core(hs, kv_mul, q.reshape(1, n_q, hs),
                          k_all[layer], v_all[layer],
                          causal_cache_mask(S, jnp.int32(pos), 1))
    got = decode_attention(q, k_all, v_all, layer, pos, kv_mul=kv_mul,
                           interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kv_mul,pos", [(1, 0), (1, 17), (2, 9), (8, 9)])
def test_decode_attention_batch_matches_core(kv_mul, pos):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (attention_core,
                                                    causal_cache_mask)
    from distributed_llama_tpu.ops.pallas_attention import \
        decode_attention_batch

    L, B, S, n_kv, hs = 2, 3, 32, 4, 128
    n_q = n_kv * kv_mul
    layer = 1
    rng = np.random.default_rng(pos * 3 + kv_mul)
    # rank-4 batched cache (L*B, S, n_kv, hs), row = layer*B + b
    k4 = jnp.asarray(rng.normal(size=(L * B, S, n_kv, hs)).astype(np.float32))
    v4 = jnp.asarray(rng.normal(size=(L * B, S, n_kv, hs)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(B, n_q, hs)).astype(np.float32))

    got = decode_attention_batch(q, k4, v4, layer, pos, kv_mul=kv_mul,
                                 interpret=True)
    mask = causal_cache_mask(S, jnp.int32(pos), 1)
    for b in range(B):
        want = attention_core(hs, kv_mul, q[b][None], k4[layer * B + b],
                              v4[layer * B + b], mask)
        np.testing.assert_allclose(np.asarray(got[b][None]),
                                   np.asarray(want), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("kv_mul", [1, 2])
def test_decode_attention_batch_ragged_pos(kv_mul):
    """Per-row position clocks (continuous batching): each row's flash walk
    must honor ITS pos, matching the per-row reference attention."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (attention_core,
                                                    causal_cache_mask)
    from distributed_llama_tpu.ops.pallas_attention import \
        decode_attention_batch

    L, B, S, n_kv, hs = 2, 3, 32, 4, 128
    n_q = n_kv * kv_mul
    layer = 1
    pos_vec = jnp.asarray([0, 17, 9], jnp.int32)
    rng = np.random.default_rng(11 + kv_mul)
    k4 = jnp.asarray(rng.normal(size=(L * B, S, n_kv, hs)).astype(np.float32))
    v4 = jnp.asarray(rng.normal(size=(L * B, S, n_kv, hs)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(B, n_q, hs)).astype(np.float32))

    got = decode_attention_batch(q, k4, v4, layer, pos_vec, kv_mul=kv_mul,
                                 interpret=True)
    for b in range(B):
        mask = causal_cache_mask(S, pos_vec[b], 1)
        want = attention_core(hs, kv_mul, q[b][None], k4[layer * B + b],
                              v4[layer * B + b], mask)
        np.testing.assert_allclose(np.asarray(got[b][None]),
                                   np.asarray(want), rtol=1e-5, atol=1e-5)


def test_decode_attention_ignores_stale_suffix():
    """Entries beyond pos (stale garbage from earlier generations) must not
    affect the result — the kernel only walks live chunks and masks within
    the last one."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_attention import decode_attention

    L, S, n_kv, hs = 1, 64, 2, 128
    rng = np.random.default_rng(0)
    k_all = rng.normal(size=(L, S, n_kv, hs)).astype(np.float32)
    v_all = rng.normal(size=(L, S, n_kv, hs)).astype(np.float32)
    q = jnp.asarray(rng.normal(size=(n_kv, hs)).astype(np.float32))
    pos = 7

    a = decode_attention(q, jnp.asarray(k_all), jnp.asarray(v_all), 0, pos,
                         kv_mul=1, interpret=True)
    k_all[:, pos + 1:] = 1e6  # poison the dead region
    v_all[:, pos + 1:] = -1e6
    b = decode_attention(q, jnp.asarray(k_all), jnp.asarray(v_all), 0, pos,
                         kv_mul=1, interpret=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_shard_shapes_have_vmem_headroom():
    """Every bench (model, tp) shard shape must admit a cache chunking
    whose scratch fits the budget, under a raised scoped-VMEM limit with
    real headroom — the 13b-tp4 margin bug (BASELINE.md r4): scratch near
    the 12 MB budget plus compiler temporaries landed 76 KB over the
    default 16 MB limit and silently fell back to the XLA path."""
    from distributed_llama_tpu.models.synth import (llama2_7b_spec,
                                                    llama2_13b_spec,
                                                    llama2_70b_spec)
    from distributed_llama_tpu.ops import pallas_attention as pa

    # the raised limit must leave a wide margin over the scratch budget,
    # not the 33% the default limit gave
    assert pa._VMEM64_PARAMS.vmem_limit_bytes >= 4 * pa._VMEM_BUDGET

    for spec in (llama2_7b_spec(), llama2_13b_spec(), llama2_70b_spec()):
        for tp in (1, 2, 4, 8):
            if spec.n_kv_heads % tp:
                continue
            n_kv = spec.n_kv_heads // tp
            for itemsize in (2, 4):  # bf16 and f32 caches
                c = pa._chunk(spec.seq_len, n_kv, spec.head_size, itemsize)
                assert c is not None, (spec.n_layers, tp, itemsize)
                assert (pa._scratch_bytes(c, n_kv, spec.head_size,
                                          itemsize)
                        <= pa._VMEM_BUDGET), (spec.n_layers, tp, itemsize)


@pytest.mark.parametrize("kv_mul,pos,t_len", [(1, 0, 16), (1, 16, 16),
                                              (1, 48, 16), (2, 0, 32),
                                              (4, 24, 16), (8, 8, 16)])
def test_prefill_attention_matches_core(kv_mul, pos, t_len):
    """The prefill flash kernel (VERDICT r4 #5) against the dense masked
    path: same causal contract (the chunk's own keys are in the cache),
    every GQA group width, first/mid/deep chunk positions."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (attention_core,
                                                    causal_cache_mask)
    from distributed_llama_tpu.ops.pallas_attention import (
        prefill_attention, supports_prefill)

    S, n_kv, hs = 64, 2, 128
    n_q = n_kv * kv_mul
    assert supports_prefill(S, hs, t_len, kv_mul)
    rng = np.random.default_rng(pos * 11 + kv_mul + t_len)
    k = jnp.asarray(rng.normal(size=(S, n_kv, hs)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(S, n_kv, hs)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(t_len, n_q, hs)).astype(np.float32))

    want = attention_core(hs, kv_mul, q, k, v,
                          causal_cache_mask(S, jnp.int32(pos), t_len))
    got = prefill_attention(q, k, v, pos, kv_mul=kv_mul, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want).reshape(t_len, n_q, hs),
        rtol=1e-5, atol=1e-5)


def test_prefill_attention_bf16_cache_and_mode():
    """bf16 cache dtype + bf16 MXU mode stay within the fast-prefill
    tolerance against the dense path run on the same bf16 cache."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (attention_core,
                                                    causal_cache_mask)
    from distributed_llama_tpu.ops.pallas_attention import prefill_attention

    S, n_kv, hs, kv_mul, t_len, pos = 64, 2, 128, 2, 16, 24
    n_q = n_kv * kv_mul
    rng = np.random.default_rng(3)
    k = jnp.asarray(rng.normal(size=(S, n_kv, hs))).astype(jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(S, n_kv, hs))).astype(jnp.bfloat16)
    q = jnp.asarray(rng.normal(size=(t_len, n_q, hs)).astype(np.float32))

    want = attention_core(hs, kv_mul, q, k.astype(jnp.float32),
                          v.astype(jnp.float32),
                          causal_cache_mask(S, jnp.int32(pos), t_len))
    got = prefill_attention(q, k, v, pos, kv_mul=kv_mul, bf16=True,
                            interpret=True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want).reshape(t_len, n_q, hs),
        rtol=0.02, atol=0.02)


def test_prefill_attention_walks_only_live_blocks():
    """Keys beyond the causal bound must not influence the result: poison
    the dead region of the cache with huge values and compare against a
    clean cache."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_attention import prefill_attention

    S, n_kv, hs, t_len, pos = 128, 2, 128, 16, 8
    rng = np.random.default_rng(5)
    k = rng.normal(size=(S, n_kv, hs)).astype(np.float32)
    v = rng.normal(size=(S, n_kv, hs)).astype(np.float32)
    q = jnp.asarray(rng.normal(size=(t_len, n_kv, hs)).astype(np.float32))

    clean = prefill_attention(q, jnp.asarray(k), jnp.asarray(v), pos,
                              kv_mul=1, interpret=True)
    live = pos + t_len
    k[live:] = 1e9
    v[live:] = -1e9
    poisoned = prefill_attention(q, jnp.asarray(k), jnp.asarray(v), pos,
                                 kv_mul=1, interpret=True)
    np.testing.assert_array_equal(np.asarray(clean), np.asarray(poisoned))
