"""Long-context smoke: an 8192-slot cache (4x the reference's fixed 2048)
through the sharded decode paths — deep-position parity and flash-kernel
chunking at scale. The reference caps seqLen at conversion time
(converter.py:80); here seq_len is free, so pin the scaling paths."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.parallel import make_mesh

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=8,
                       n_kv_heads=4, vocab_size=96, seq_len=8192)


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=6, scale=0.2)


@pytest.mark.parametrize("sp,tp", [(1, 2), (4, 1), (2, 2)])
def test_deep_position_decode_parity(params, sp, tp):
    """Decode at position ~8k: sharded (sp ring / tp bands) logits ==
    single-chip logits, with history written deep in the cache."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)
    from distributed_llama_tpu.parallel import (make_sharded_forward,
                                                shard_cache, shard_params)

    # write a short history at a DEEP offset (positions 8000..8004), then
    # decode at 8005 — exercises chunk arithmetic far beyond 2048
    history = [(5, 8000), (9, 8001), (17, 8002), (3, 8003), (40, 8004)]

    dev = params_to_device(params)
    c = init_cache(SPEC)
    for t, p in history:
        _, c = forward(SPEC, dev, c, jnp.asarray([t], jnp.int32),
                       jnp.int32(p))
    want, _ = forward(SPEC, dev, c, jnp.asarray([7], jnp.int32),
                      jnp.int32(8005))

    mesh = make_mesh(sp=sp, tp=tp)
    fwd = make_sharded_forward(SPEC, mesh)
    ps = shard_params(params, mesh)
    cs = shard_cache(init_cache(SPEC), mesh)
    for t, p in history:
        _, cs = fwd(ps, cs, jnp.asarray([t], jnp.int32), jnp.int32(p))
    got, _ = fwd(ps, cs, jnp.asarray([7], jnp.int32), jnp.int32(8005))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=3e-5)


def test_flash_decode_chunking_covers_8k():
    """The flash-decode kernel's VMEM chunk table must place 8192-slot
    caches at 7B-like head shapes (f32 and bf16), and the batch/ragged
    paths share the same gate."""
    from distributed_llama_tpu.ops.pallas_attention import _chunk, supports

    for itemsize in (2, 4):
        assert supports(8192, 128, 1, n_kv=8, itemsize=itemsize)
        assert _chunk(8192, 8, 128, itemsize) is not None
    # 7B MHA shape (32 kv heads) at 8k, f32: still places a chunk
    assert _chunk(8192, 32, 128, 4) is not None


def test_long_context_generate_roundtrip(params):
    """Chunked prefill of a 40-token prompt + fused decode on the 8192
    cache, vs the per-token path — stream equality end to end."""
    from distributed_llama_tpu.runtime.generate import (Engine, generate,
                                                        generate_fast)
    from distributed_llama_tpu.runtime.sampling import Sampler

    class _Tok:
        def encode(self, text, bos=True, eos=False):
            return [1] + [3 + (b % 90) for b in text.encode()]

        def decode_piece(self, prev, tok):
            return b"?"

    tok = _Tok()
    prompt = "x" * 39
    ref, _ = generate(Engine(SPEC, params), tok,
                      Sampler(SPEC.vocab_size, 0.9, 0.9, 7), prompt,
                      steps=50, quiet=True)
    got, _ = generate_fast(Engine(SPEC, params), tok,
                           Sampler(SPEC.vocab_size, 0.9, 0.9, 7), prompt,
                           steps=50, quiet=True, prefill_chunk=16)
    assert got == ref
