"""Round-trip tests for the .bin format reader/writer."""

import numpy as np
import pytest

from distributed_llama_tpu.io.loader import Q40Weight, load_model, read_spec, write_model
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType, dequantize_q40
from distributed_llama_tpu.utils.rng import Xorshift64

TINY = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=96, seq_len=32)


def _synth_tensors(spec, seed=12345):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32)

    layers = {name: t(spec.n_layers, *shape)
              for name, shape in spec.layer_matmul_shapes()}
    return {
        "tok_embedding": t(spec.vocab_size, spec.dim),
        "rms_att": t(spec.n_layers, spec.dim),
        "rms_ffn": t(spec.n_layers, spec.dim),
        "rms_final": t(spec.dim),
        "wcls": t(spec.vocab_size, spec.dim),
        **layers,
    }


@pytest.mark.parametrize("ftype", [FloatType.F32, FloatType.F16, FloatType.Q40])
def test_write_read_roundtrip(tmp_path, ftype):
    spec = TransformerSpec(**{**TINY.__dict__, "weights_float_type": ftype})
    tensors = _synth_tensors(spec)
    path = str(tmp_path / "model.bin")
    write_model(path, spec, tensors)

    spec2 = read_spec(path, ftype)
    assert spec2.dim == spec.dim and spec2.hidden_dim == spec.hidden_dim
    assert spec2.kv_dim == spec.dim * spec.n_kv_heads // spec.n_heads

    _, params = load_model(path, spec2)
    np.testing.assert_array_equal(params["tok_embedding"],
                                  tensors["tok_embedding"])
    np.testing.assert_array_equal(params["rms_final"], tensors["rms_final"])

    wq = params["wq"]
    if ftype == FloatType.F32:
        np.testing.assert_array_equal(wq, tensors["wq"])
    elif ftype == FloatType.F16:
        np.testing.assert_array_equal(
            wq, tensors["wq"].astype(np.float16))
    else:
        assert isinstance(wq, Q40Weight)
        assert wq.qs.shape == (spec.n_layers, spec.dim, spec.dim // 32, 16)
        deq = dequantize_q40(wq.qs, wq.d16)
        # Q40 is lossy: delta = amax/8, error <= ~delta/2 (+ f16 rounding)
        amax = np.abs(tensors["wq"]).reshape(
            spec.n_layers, spec.dim, -1, 32).max(axis=-1)
        # the +8.5/clamp-15 code map clamps the -amax extreme to code 15,
        # losing up to a full delta there (delta = amax/8)
        tol = (amax / 8 * 1.02 + 1e-3)[..., None]
        err = np.abs(deq.reshape(spec.n_layers, spec.dim, -1, 32)
                     - tensors["wq"].reshape(spec.n_layers, spec.dim, -1, 32))
        assert np.all(err <= tol)


def test_file_size_accounting(tmp_path):
    """Byte-exact size math vs the known 7B test constants from the reference
    integration test (transformer-tasks-test.cpp:544-548): blockBytes must be
    809533440 for the 1-layer 7B F32 shape."""
    spec7b = TransformerSpec(dim=4096, hidden_dim=11008, n_layers=1, n_heads=32,
                             n_kv_heads=32, vocab_size=32000, seq_len=2048)
    assert spec7b.block_bytes() == 809533440
    assert spec7b.vocab_size * spec7b.dim * 4 == 524288000  # beforeBlockBytes
    after = spec7b.dim * 4 + spec7b.rope_gap_bytes + spec7b.matmul_bytes(
        (spec7b.vocab_size, spec7b.dim))
    assert after == 525352960  # afterBlockBytes
    assert spec7b.file_size() == 524288000 + 809533440 + 525352960 + 28


def test_truncated_file_rejected(tmp_path):
    spec = TINY
    tensors = _synth_tensors(spec)
    path = str(tmp_path / "model.bin")
    write_model(path, spec, tensors)
    with open(path, "r+b") as f:
        f.truncate(spec.file_size() - 100)
    with pytest.raises(ValueError, match="size mismatch"):
        load_model(path, spec)


def test_xorshift_stream_vectorized_matches_scalar():
    a = Xorshift64(800000010)
    b = Xorshift64(800000010)
    xs = a.f32_array(1000)
    ys = np.array([b.f32() for _ in range(1000)], dtype=np.float32)
    np.testing.assert_array_equal(xs, ys)


def test_13b_70b_q40_size_anchors():
    """Q40 file sizes for the reference's published model set (README.md:
    90-92: 7B 3.95 / 13B 7.35 / 70B 36.98 GB) — byte-exact accounting for
    the GQA (70B) layout included."""
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.ops.quants import FloatType

    spec13 = TransformerSpec(dim=5120, hidden_dim=13824, n_layers=40,
                             n_heads=40, n_kv_heads=40, vocab_size=32000,
                             seq_len=2048, weights_float_type=FloatType.Q40)
    assert spec13.file_size() == 7887097884  # 7.345 GiB
    spec70 = TransformerSpec(dim=8192, hidden_dim=28672, n_layers=80,
                             n_heads=64, n_kv_heads=8, vocab_size=32000,
                             seq_len=2048, weights_float_type=FloatType.Q40)
    assert spec70.file_size() == 39706066972  # 36.979 GiB
