"""Per-dispatch scheduler census (ISSUE 16): ring accounting, the
no-wall-clock determinism contract on the virtual clock, and the
accounting plane's Prometheus surface (pre-registered series)."""

import argparse
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

from distributed_llama_tpu.obs.ledger import CensusRing  # noqa: E402


def _args(**kw):
    base = dict(slots=4, seed=7, page_size=4, kv_pages=20, block_steps=2,
                spec_k=0, requests=16, rate=0.5, arrivals="bursty")
    base.update(kw)
    return argparse.Namespace(**base)


@pytest.fixture(scope="module")
def make_engine():
    from loadcheck import build_engine_factory

    return build_engine_factory(_args())


def _drive(make_engine, **overrides):
    from loadcheck import _load_spec, _policy
    from loadgen import drive_engine, generate_trace

    args = _args()
    trace = generate_trace(_load_spec(args.rate, args), args.seed)
    eng = make_engine(**overrides)
    drive_engine(eng, trace, _policy())
    return eng


# ---------------------------------------------------------------- ring

def test_census_record_accumulates_totals():
    ring = CensusRing(slots=4)
    ring.record("decode", steps=2, active=3, parked={"pool_dry": 1},
                queue_depth=2, pages_held=10)
    ring.record("decode", steps=1, active=4, parked={}, queue_depth=0,
                pages_held=12)
    t = ring.totals()
    assert t["dispatches"] == 2
    assert t["steps"] == 3
    assert t["row_steps"] == 3 * 2 + 4 * 1
    assert t["stall_steps"] == (1 + 2) * 2  # (parked + queued) x steps
    assert t["page_steps"] == 10 * 2 + 12 * 1
    ring.count_tokens("decode", 5)
    ring.count_tokens("prefill", 8)
    assert ring.totals()["tokens"] == {"decode": 5, "prefill": 8,
                                       "spec": 0}


def test_census_ring_bounds_tail_but_keeps_totals():
    ring = CensusRing(slots=2, keep=8)
    for _ in range(20):
        ring.record("decode", steps=1, active=1, parked={},
                    queue_depth=0, pages_held=1)
    assert len(ring.tail(64)) == 8  # ring capped
    assert ring.totals()["dispatches"] == 20  # totals are not
    assert len(ring.tail(3)) == 3


def test_census_records_carry_no_wall_clock():
    """The determinism contract: a census record must serialize without
    any wall-time field — rings from identical virtual-clock runs are
    compared byte-for-byte."""
    ring = CensusRing(slots=4)
    ring.record("decode", steps=2, active=1, parked={"pool_dry": 1},
                queue_depth=1, pages_held=4, tier_pages={"hbm": 4})
    ring.record("prefill", steps=0, active=0, parked={}, queue_depth=0,
                pages_held=0, prefill_tokens=8)
    for rec in ring.tail(2):
        assert not {"ts", "t", "dt_s", "wall_s"} & set(rec)
    decode, prefill = ring.tail(2)
    assert decode["tier_pages"] == {"hbm": 4}
    assert prefill["prefill_tokens"] == 8
    assert prefill["steps"] == 0  # prefill never rides step conservation


def test_census_to_json_shape():
    ring = CensusRing(slots=4)
    ring.record("decode", steps=1, active=2, parked={}, queue_depth=0,
                pages_held=6)
    doc = ring.to_json(tail=16)
    assert doc["kind"] == "dllama-sched-census"
    assert doc["version"] == 1
    assert doc["slots"] == 4
    assert doc["totals"]["row_steps"] == 2
    assert len(doc["ring"]) == 1


# ------------------------------------------------- engine determinism

def test_census_deterministic_on_virtual_clock(make_engine):
    """Same seed, same trace, two fresh engines: the census rings must
    be BYTE-identical — the fleetcheck/ci determinism property."""
    a = _drive(make_engine)
    b = _drive(make_engine)
    ja = json.dumps(a.sched_census.to_json(tail=256), sort_keys=True)
    jb = json.dumps(b.sched_census.to_json(tail=256), sort_keys=True)
    assert ja == jb
    assert a.sched_census.totals()["dispatches"] > 0


def test_census_matches_engine_stats(make_engine):
    eng = _drive(make_engine)
    t = eng.sched_census.totals()
    assert t["steps"] == eng.stats.steps
    assert t["row_steps"] == eng.stats.sum_active
    assert (t["tokens"]["decode"] + t["tokens"]["prefill"]
            == eng.stats.tokens)


def test_spec_dispatches_counted(make_engine):
    eng = _drive(make_engine, spec_k=2)
    t = eng.sched_census.totals()
    assert t["tokens"]["spec"] > 0
    assert any(r["kind"] == "spec" for r in eng.sched_census.tail(256))


# ------------------------------------------------ prometheus surface

def test_accounting_series_preregistered_at_zero(make_engine):
    """Every accounting series must exist in the exposition from step
    zero (a dashboard must see 0, not an absent series), including the
    per-class queue gauge and the request-cost histograms."""
    eng = make_engine()
    text = eng._obs.registry.expose()
    for kind in ("decode", "prefill", "spec"):
        assert f'dllama_dispatch_tokens_total{{kind="{kind}"}} 0' in text
    for cause in ("pool_dry", "promo_pending", "prefill_hold",
                  "queue_wait", "handoff_wait"):
        assert (f'dllama_stall_seconds_total{{cause="{cause}"}} 0'
                in text)
    assert 'dllama_page_seconds_total{class="default"} 0' in text
    assert 'dllama_queue_depth_by_class{class="default"} 0' in text
    assert "dllama_request_cost_dispatch_seconds" in text
    assert "dllama_request_cost_page_seconds" in text
    assert "dllama_request_cost_stall_seconds" in text
    assert "dllama_request_queue_wait_by_class_seconds" in text


def test_accounting_series_move_under_load(make_engine):
    eng = _drive(make_engine)
    from distributed_llama_tpu.obs.fleet import parse_metrics

    samples = parse_metrics(eng._obs.registry.expose())
    decode = samples.get('dllama_dispatch_tokens_total{kind="decode"}', 0)
    prefill = samples.get(
        'dllama_dispatch_tokens_total{kind="prefill"}', 0)
    assert decode + prefill == eng.stats.tokens
    page_s = sum(v for k, v in samples.items()
                 if k.startswith("dllama_page_seconds_total{"))
    assert page_s > 0.0
    # request-cost histograms observed once per retired request
    closes = sum(v for k, v in samples.items() if k.startswith(
        "dllama_request_cost_dispatch_seconds_count{"))
    assert closes == eng.ledger_book.closed_n


def test_class_queue_depth_zeroes_absent_classes():
    from distributed_llama_tpu.obs.metrics import Registry
    from distributed_llama_tpu.obs.trace import EngineMetrics

    m = EngineMetrics(Registry())
    m.set_class_queue_depth({"interactive": 3, "batch": 1})
    text = m.registry.expose()
    assert 'dllama_queue_depth_by_class{class="interactive"} 3' in text
    m.set_class_queue_depth({"batch": 2})
    text = m.registry.expose()
    # a drained class must read 0, not its stale last value
    assert 'dllama_queue_depth_by_class{class="interactive"} 0' in text
    assert 'dllama_queue_depth_by_class{class="batch"} 2' in text
