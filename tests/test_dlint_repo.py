"""Tier-1 repo gate: dlint's lint head over the real package must report
ZERO findings beyond the checked-in baseline — new hazards fail `pytest
tests/` directly, no separate CI lane needed. Plus repo hygiene: no
tracked bytecode, probe scripts excluded from the lint surface."""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

from distributed_llama_tpu.analysis.__main__ import (DEFAULT_BASELINE,
                                                     PACKAGE_DIR, REPO_ROOT)
from distributed_llama_tpu.analysis.lint import (apply_baseline, lint_paths,
                                                 load_baseline,
                                                 package_files)


def test_package_has_no_new_lint_findings():
    findings = lint_paths(package_files(PACKAGE_DIR), REPO_ROOT)
    baseline = load_baseline(DEFAULT_BASELINE)
    new, _, stale = apply_baseline(findings, baseline)
    assert not new, "new dlint findings (fix, pragma with a reason, or " \
        "regenerate the baseline):\n" + "\n".join(f.render() for f in new)
    assert not stale, "stale baseline entries (findings fixed — run " \
        "--write-baseline to prune):\n" + "\n".join(stale)


def test_baseline_has_no_runtime_entries():
    # runtime/ debt is pragma'd with reasons at the site, never
    # grandfathered silently — the satellite contract of this gate
    assert not [k for k in load_baseline(DEFAULT_BASELINE)
                if "/runtime/" in k]


def test_lint_surface_excludes_tools_and_tests():
    files = {p.as_posix() for p in package_files(PACKAGE_DIR)}
    assert not any("/tools/" in f or "/tests/" in f for f in files)
    assert not any("__pycache__" in f for f in files)
    assert any(f.endswith("runtime/continuous.py") for f in files)


def test_cli_all_exits_zero_on_repo():
    # the acceptance-criteria invocation, end to end in a fresh process
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_llama_tpu.analysis", "--all"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=600,
        env={"PATH": "/usr/bin:/bin", "HOME": "/tmp",
             "PYTHONPATH": str(REPO_ROOT)})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new finding(s)" in proc.stdout
    assert proc.stdout.count("FAIL") == 0


def test_cli_accepts_directory_paths(capsys):
    # a directory argument scans everything under it (a bare-path typo or
    # dir would otherwise read as a clean 0-file run)
    from distributed_llama_tpu.analysis.__main__ import main

    rc = main(["--lint", str(PACKAGE_DIR / "runtime")])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 new finding(s)" in out and "1 file(s)" not in out


def test_write_baseline_refuses_partial_scans(tmp_path):
    # rewriting the GLOBAL baseline from a one-file scan would drop every
    # grandfathered entry for unscanned files — must be a usage error
    from distributed_llama_tpu.analysis.__main__ import main

    target = PACKAGE_DIR / "runtime" / "continuous.py"
    rc = main(["--lint", "--write-baseline",
               "--baseline", str(tmp_path / "b.txt"), str(target)])
    assert rc == 2
    assert not (tmp_path / "b.txt").exists()


def test_no_bytecode_or_scratch_output_is_tracked():
    tracked = subprocess.run(
        ["git", "ls-files"], cwd=REPO_ROOT, capture_output=True,
        text=True, check=True).stdout.splitlines()
    offenders = [t for t in tracked
                 if "__pycache__" in t or t.endswith(".pyc")
                 or t.startswith("tools/dlint_cache/")]
    assert not offenders, offenders
    gitignore = (Path(REPO_ROOT) / ".gitignore").read_text()
    for pattern in ("__pycache__/", "*.pyc", "tools/dlint_cache/"):
        assert pattern in gitignore, f"{pattern} missing from .gitignore"
