"""Golden single-block forward parity test.

Port of the reference integration test (src/transformer-tasks-test.cpp): a
7B-shaped 1-layer F32 model whose block weights and input x are drawn from
xorshift seed 800000010 scaled by 1/120, run one block at pos=0, and compare x
against the reference's hard-coded 4096-float expected output (extracted to
tests/fixtures/golden_block_7b_f32.npy by tools/extract_golden_fixture.py).
Tolerance 1e-5 per element, same as the reference (:582).

The weight stream order is the .bin block layout the reference test fills:
rmsAtt, rmsFfn, wq, wk, wv, wo, w1, w2, w3 (each row-major (d, n)), then x.
"""

import os

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.utils.native import xorshift_fill

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "golden_block_7b_f32.npy")

SPEC = TransformerSpec(dim=4096, hidden_dim=11008, n_layers=1, n_heads=32,
                       n_kv_heads=32, vocab_size=32000, seq_len=2048)


@pytest.fixture(scope="module")
def golden_setup():
    state = 800000010
    dim, hid = SPEC.dim, SPEC.hidden_dim
    sizes = [("rms_att", (dim,)), ("rms_ffn", (dim,)),
             ("wq", (dim, dim)), ("wk", (dim, dim)), ("wv", (dim, dim)),
             ("wo", (dim, dim)), ("w1", (hid, dim)), ("w2", (dim, hid)),
             ("w3", (hid, dim))]
    lw = {}
    for name, shape in sizes:
        state, arr = xorshift_fill(state, int(np.prod(shape)), 120.0)
        lw[name] = arr.reshape(shape)
    state, x = xorshift_fill(state, dim, 120.0)
    expected = np.load(FIXTURE)
    return lw, x, expected


def test_golden_block_forward(golden_setup):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import _layer

    lw, x, expected = golden_setup
    lwj = {k: jnp.asarray(v) for k, v in lw.items()}
    k_all = jnp.zeros(
        (1, SPEC.seq_len, SPEC.n_kv_heads, SPEC.head_size), jnp.float32)
    v_all = jnp.zeros_like(k_all)
    out, _, _ = _layer(SPEC, jnp.asarray(x)[None, :], lwj, k_all, v_all,
                       jnp.int32(0), jnp.int32(0),
                       jnp.arange(1, dtype=jnp.int32))
    got = np.asarray(out[0])
    err = np.abs(got - expected)
    assert err.max() <= 1e-5, (
        f"max err {err.max():.3e} at {err.argmax()}: "
        f"{got[err.argmax()]!r} != {expected[err.argmax()]!r}")
