"""Checkpoint/resume: a split generation must equal the unsplit one."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.runtime.checkpoint import (load_generation_state,
                                                      save_generation_state)
from distributed_llama_tpu.runtime.generate import Engine, generate
from distributed_llama_tpu.runtime.sampling import Sampler

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=300, seq_len=32)


class _IdTokenizer:
    """encode -> [BOS, ...bytes]; decode unused by these tests."""

    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"?"


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=9, scale=0.3)


def _sampler(seed=77):
    return Sampler(SPEC.vocab_size, temperature=0.9, topp=0.9, seed=seed)


def test_split_generation_is_bit_identical(tmp_path, params):
    tok = _IdTokenizer()

    full_engine = Engine(SPEC, params)
    full, fstats = generate(full_engine, tok, _sampler(), "ab", steps=12,
                            quiet=True)

    eng1 = Engine(SPEC, params)
    s1 = _sampler()
    part1, stats1 = generate(eng1, tok, s1, "ab", steps=5, quiet=True)
    ckpt = str(tmp_path / "gen.npz")
    save_generation_state(ckpt, eng1, s1, stats1.final_pos,
                          stats1.final_token, part1)

    eng2 = Engine(SPEC, params)  # fresh engine: cache restored from disk
    s2 = _sampler(seed=123)      # wrong seed: must be overwritten by load
    pos, token, prev, rest = load_generation_state(ckpt, eng2, s2)
    assert prev == part1 and pos == stats1.final_pos and rest == []
    part2, _ = generate(eng2, tok, s2, "IGNORED", steps=12 - pos, quiet=True,
                        resume=(pos, token))

    assert part1 + part2 == full


def test_split_mid_prompt_preserves_forced_tail(tmp_path, params):
    """Checkpointing BEFORE the prompt is consumed must carry the unconsumed
    forced tokens into the resumed run (review finding: without prompt_rest
    the continuation samples where the unsplit run forces)."""
    tok = _IdTokenizer()
    long_prompt = "abcdefg"  # 8 tokens with BOS: consumed through pos 7

    full_engine = Engine(SPEC, params)
    full, _ = generate(full_engine, tok, _sampler(), long_prompt, steps=12,
                       quiet=True)

    eng1 = Engine(SPEC, params)
    s1 = _sampler()
    part1, stats1 = generate(eng1, tok, s1, long_prompt, steps=4, quiet=True)
    assert stats1.prompt_rest  # split fell inside the prompt
    ckpt = str(tmp_path / "gen.npz")
    save_generation_state(ckpt, eng1, s1, stats1.final_pos,
                          stats1.final_token, part1, stats1.prompt_rest)

    eng2 = Engine(SPEC, params)
    s2 = _sampler(seed=99)
    pos, token, prev, rest = load_generation_state(ckpt, eng2, s2)
    assert rest == stats1.prompt_rest
    part2, _ = generate(eng2, tok, s2, "IGNORED", steps=12 - pos, quiet=True,
                        resume=(pos, token), resume_prompt=rest)
    assert part1 + part2 == full


def test_fast_resume_matches_unsplit_fast(tmp_path, params):
    """The fused on-device loop must resume from a checkpoint: fast(5) +
    save + load + fast(7) == fast(12) token-for-token (PARITY.md round-1
    limitation removed)."""
    from distributed_llama_tpu.runtime.generate import generate_fast

    tok = _IdTokenizer()

    full_engine = Engine(SPEC, params)
    full, _ = generate_fast(full_engine, tok, _sampler(), "ab", steps=12,
                            quiet=True)

    eng1 = Engine(SPEC, params)
    s1 = _sampler()
    part1, stats1 = generate_fast(eng1, tok, s1, "ab", steps=5, quiet=True)
    assert stats1.final_pos == 5  # resumable: no early BOS
    ckpt = str(tmp_path / "gen.npz")
    save_generation_state(ckpt, eng1, s1, stats1.final_pos,
                          stats1.final_token, part1, stats1.prompt_rest)

    eng2 = Engine(SPEC, params)
    s2 = _sampler(seed=123)  # wrong seed: must be overwritten by load
    pos, token, prev, rest = load_generation_state(ckpt, eng2, s2)
    part2, _ = generate_fast(eng2, tok, s2, "IGNORED", steps=12 - pos,
                             quiet=True, resume=(pos, token),
                             resume_prompt=rest)
    assert part1 + part2 == full


def test_fast_resume_mid_prompt(tmp_path, params):
    """A fused resume that lands inside the prompt must keep forcing the
    unconsumed prompt tail."""
    from distributed_llama_tpu.runtime.generate import generate_fast

    tok = _IdTokenizer()
    long_prompt = "abcdefg"

    full_engine = Engine(SPEC, params)
    full, _ = generate_fast(full_engine, tok, _sampler(), long_prompt,
                            steps=12, quiet=True)

    eng1 = Engine(SPEC, params)
    s1 = _sampler()
    part1, stats1 = generate_fast(eng1, tok, s1, long_prompt, steps=4,
                                  quiet=True)
    assert stats1.prompt_rest  # split fell inside the prompt
    ckpt = str(tmp_path / "gen.npz")
    save_generation_state(ckpt, eng1, s1, stats1.final_pos,
                          stats1.final_token, part1, stats1.prompt_rest)

    eng2 = Engine(SPEC, params)
    s2 = _sampler(seed=99)
    pos, token, prev, rest = load_generation_state(ckpt, eng2, s2)
    assert rest == stats1.prompt_rest
    part2, _ = generate_fast(eng2, tok, s2, "IGNORED", steps=12 - pos,
                             quiet=True, resume=(pos, token),
                             resume_prompt=rest)
    assert part1 + part2 == full


def test_fast_resume_crosses_loops(tmp_path, params):
    """Per-step save -> fused resume and fused save -> per-step resume both
    reproduce the unsplit stream (the two loops share one checkpoint
    format and position/RNG contract)."""
    from distributed_llama_tpu.runtime.generate import generate_fast

    tok = _IdTokenizer()
    full_engine = Engine(SPEC, params)
    full, _ = generate(full_engine, tok, _sampler(), "ab", steps=12,
                       quiet=True)

    # per-step first half, fused second half
    eng1 = Engine(SPEC, params)
    s1 = _sampler()
    part1, st1 = generate(eng1, tok, s1, "ab", steps=5, quiet=True)
    ckpt = str(tmp_path / "a.npz")
    save_generation_state(ckpt, eng1, s1, st1.final_pos, st1.final_token,
                          part1, st1.prompt_rest)
    eng2 = Engine(SPEC, params)
    s2 = _sampler(seed=5)
    pos, token, prev, rest = load_generation_state(ckpt, eng2, s2)
    part2, _ = generate_fast(eng2, tok, s2, "IGNORED", steps=12 - pos,
                             quiet=True, resume=(pos, token),
                             resume_prompt=rest)
    assert part1 + part2 == full

    # fused first half, per-step second half
    eng3 = Engine(SPEC, params)
    s3 = _sampler()
    part3, st3 = generate_fast(eng3, tok, s3, "ab", steps=5, quiet=True)
    ckpt2 = str(tmp_path / "b.npz")
    save_generation_state(ckpt2, eng3, s3, st3.final_pos, st3.final_token,
                          part3, st3.prompt_rest)
    eng4 = Engine(SPEC, params)
    s4 = _sampler(seed=6)
    pos, token, prev, rest = load_generation_state(ckpt2, eng4, s4)
    part4, _ = generate(eng4, tok, s4, "IGNORED", steps=12 - pos, quiet=True,
                        resume=(pos, token), resume_prompt=rest)
    assert part3 + part4 == full


def test_load_rejects_spec_mismatch(tmp_path, params):
    eng = Engine(SPEC, params)
    s = _sampler()
    ckpt = str(tmp_path / "gen.npz")
    save_generation_state(ckpt, eng, s, 3, 7, [])

    other_spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=2,
                                 n_heads=4, n_kv_heads=2, vocab_size=300,
                                 seq_len=64)  # different seq_len
    other = Engine(other_spec, synth_params(other_spec, q40=False, seed=9,
                                            scale=0.3))
    with pytest.raises(ValueError, match="header"):
        load_generation_state(ckpt, other, s)


def test_checkpoint_stores_live_prefix_only(tmp_path, params):
    import os

    eng = Engine(SPEC, params)
    s = _sampler()
    p_small = str(tmp_path / "small.npz")
    p_big = str(tmp_path / "big.npz")
    save_generation_state(p_small, eng, s, 2, 7, [])
    save_generation_state(p_big, eng, s, SPEC.seq_len, 7, [])
    assert os.path.getsize(p_small) < os.path.getsize(p_big)


def test_load_rejects_cache_dtype_mismatch(tmp_path, params):
    import jax.numpy as jnp

    eng = Engine(SPEC, params)  # f32 cache
    s = _sampler()
    ckpt = str(tmp_path / "gen.npz")
    save_generation_state(ckpt, eng, s, 3, 7, [])

    eng_bf16 = Engine(SPEC, params, cache_dtype=jnp.bfloat16)
    with pytest.raises(ValueError, match="cache dtype"):
        load_generation_state(ckpt, eng_bf16, s)
