"""Jaxpr contract head: pin collective count + KV donation + shape
stability on the tiny synth model, under JAX_PLATFORMS=cpu (conftest).

These run the SAME contract functions the `--contracts` CLI head runs, so
a contract that drifts fails here first — in tier-1, before any bench run
could notice the regression the slow way."""

from __future__ import annotations

from distributed_llama_tpu.analysis.jaxpr_contracts import (
    contract_decode_donation, contract_decode_donation_paged,
    contract_decode_shape_stability, contract_tp_collectives,
    run_contracts, walk_fn_eqns)
from distributed_llama_tpu.models.synth import small_bench_spec
from distributed_llama_tpu.ops.quants import FloatType


def _spec():
    return small_bench_spec(weights_float_type=FloatType.F32)


def test_tp_collectives_match_analytic_model():
    # the counts are part of the public claim: ref 4 all_gathers/layer +
    # logits; fused 2 psums/layer + logits — HALF the launches
    L = _spec().n_layers
    r = contract_tp_collectives(_spec(), tp=4, scheme="ref")
    assert r.ok, r.detail
    assert f"{4 * L + 1} collectives" in r.detail
    r = contract_tp_collectives(_spec(), tp=4, scheme="fused")
    assert r.ok, r.detail
    assert f"{2 * L + 1} collectives" in r.detail
    assert "'psum': " + str(2 * L) in r.detail


def test_tp_collectives_default_scheme_is_env(monkeypatch):
    # scheme=None resolves DLLAMA_TP_SCHEME exactly like the runtime
    monkeypatch.setenv("DLLAMA_TP_SCHEME", "ref")
    r = contract_tp_collectives(_spec(), tp=4)
    assert r.ok and "[ref]" in r.name, (r.name, r.detail)


def test_decode_step_kv_cache_donation_holds():
    r = contract_decode_donation(_spec(), slots=4)
    assert r.ok, r.detail
    assert "2 aliased" in r.detail  # both KV planes, not just one


def test_decode_step_paged_kv_donation_holds():
    # J002 must hold under the paged layout too: both page-pool planes
    # aliased through the lowering, with the page table riding alongside
    r = contract_decode_donation_paged(_spec(), slots=4, page_size=16)
    assert r.ok, r.detail
    assert "2 aliased" in r.detail


def test_decode_step_shape_stability_holds():
    r = contract_decode_shape_stability(_spec(), slots=4)
    assert r.ok, r.detail


def test_run_contracts_reports_all_and_passes():
    results = run_contracts(_spec())
    # J001 runs once per scheme (ref + fused + overlap) for the decode
    # forward, the speculative K-query verify dispatch (ISSUE 7/10), AND
    # the token-budget mixed dispatch (ISSUE 18), J002 once per cache
    # layout (contiguous + paged) — every schedule/layout stays pinned
    assert [r.contract for r in results] == ["J001"] * 9 + ["J002",
                                                            "J002",
                                                            "J003"]
    assert {r.name for r in results if r.contract == "J001"} == {
        "tp_collectives[ref]", "tp_collectives[fused]",
        "tp_collectives[overlap]",
        "verify_collectives[ref]", "verify_collectives[fused]",
        "verify_collectives[overlap]",
        "mixed_collectives[ref]", "mixed_collectives[fused]",
        "mixed_collectives[overlap]"}
    assert all(r.ok for r in results), [r.detail for r in results]


def test_contract_failure_becomes_finding_not_crash():
    # a spec that cannot shard onto the mesh must yield a failed result
    # (the CLI turns it into a finding), never an exception
    bad = small_bench_spec(weights_float_type=FloatType.F32,
                           vocab_size=1023)  # 1023 % tp != 0
    results = run_contracts(bad)
    assert any(not r.ok for r in results)
    # even on a raised error, results keep the documented J-ids (the CLI
    # and contract_findings key on them)
    assert [r.contract for r in results] == ["J001"] * 9 + ["J002",
                                                            "J002",
                                                            "J003"]


def test_walk_fn_eqns_shim_still_works():
    # the tests/jaxpr_utils.py re-export shim keeps old callers alive
    import jax.numpy as jnp

    from jaxpr_utils import walk_fn_eqns as shimmed

    assert shimmed is walk_fn_eqns
    eqns = shimmed(lambda x: jnp.sin(x) + 1.0, jnp.zeros((4,)))
    assert any(e.primitive.name == "sin" for e in eqns)
