"""Request-level cost ledger (ISSUE 16): unit accounting on
obs/ledger.py, and the ledger-vs-census conservation equalities under
chaos — cancel storm, kill-mid-decode recovery, the two-pool handoff
seam — with zero orphaned or duplicated bills."""

import argparse
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

from distributed_llama_tpu.obs.ledger import (LedgerBook,  # noqa: E402
                                              STALL_CAUSES)


def _args(**kw):
    """The costcheck CLI's engine/trace knobs as a namespace (the legs
    are shared with tools/costcheck.py — one conservation harness)."""
    base = dict(slots=4, seed=7, page_size=4, kv_pages=20, block_steps=2,
                spec_k=0, requests=16, rate=0.5, arrivals="bursty",
                two_pool_rate=0.25)
    base.update(kw)
    return argparse.Namespace(**base)


@pytest.fixture(scope="module")
def make_engine():
    from loadcheck import build_engine_factory

    return build_engine_factory(_args())


# ---------------------------------------------------------------- unit

def test_ledger_charges_accumulate():
    book = LedgerBook()
    led = book.open_request(1, "interactive")
    led.charge_rows(3, 0.25)
    led.charge_tokens(2)
    led.charge_prefill(chunks=2, tokens=8, dt_s=0.5)
    led.charge_pages(4, 3, 0.1)
    led.charge_stall("queue_wait", 2, 0.05)
    led.charge_ici(1024.0)
    led.charge_dcn(2, 8192)
    led.charge_spec(4, 1)
    snap = led.snapshot()
    assert snap["decode_row_steps"] == 3
    assert snap["tokens"] == 2  # prefill echo tokens bill separately
    assert snap["prefill_tokens"] == 8
    assert snap["prefill_chunks"] == 2
    assert snap["page_steps"] == 4 * 3
    assert snap["stall_steps"] == {"queue_wait": 2}
    assert snap["ici_bytes"] == 1024.0
    assert snap["dcn_pages"] == 2 and snap["dcn_bytes"] == 8192
    assert snap["spec_proposed"] == 4 and snap["spec_accepted"] == 1
    assert snap["spec_wasted"] == 3


def test_ledger_reps_doubles_only_the_ledger_side():
    """The double-count-dispatch mutation's lever: reps multiplies the
    ledger charge (the census side counts once, independently)."""
    book = LedgerBook()
    led = book.open_request(1)
    led.charge_rows(5, 0.1, reps=2)
    led.charge_pages(3, 5, 0.1, reps=2)
    snap = led.snapshot()
    assert snap["decode_row_steps"] == 10
    assert snap["page_steps"] == 30


def test_snapshot_merges_carried_bill():
    book = LedgerBook()
    led = book.open_request(7, "batch",
                            carried={"tokens": 10, "page_steps": 40,
                                     "stall_steps": {"pool_dry": 3},
                                     "dcn_bytes": 512})
    led.charge_tokens(5)
    led.charge_stall("pool_dry", 2, 0.1)
    snap = led.snapshot()
    assert snap["tokens"] == 15
    assert snap["page_steps"] == 40
    assert snap["stall_steps"]["pool_dry"] == 5
    assert snap["dcn_bytes"] == 512


def test_open_and_close_are_idempotent_no_duplicate_folds():
    book = LedgerBook()
    led = book.open_request(3, "interactive")
    assert book.open_request(3) is led  # re-open returns the same bill
    led.charge_tokens(4)
    first = book.close_request(3, "done")
    assert first is not None and first["tokens"] == 4
    assert book.close_request(3, "done") is None  # second close: no-op
    assert book.grand_totals()["tokens"] == 4  # folded exactly once
    assert book.opened_n == 1 and book.closed_n == 1 and book.n_open == 0


def test_grand_totals_span_open_and_closed():
    book = LedgerBook()
    book.open_request(1).charge_tokens(3)
    book.open_request(2).charge_tokens(5)
    book.close_request(1, "done")
    assert book.grand_totals(include_open=True)["tokens"] == 8
    assert book.grand_totals(include_open=False)["tokens"] == 3
    assert book.n_open == 1


def test_class_rollup_recomputes_ratios_from_sums():
    book = LedgerBook()
    a = book.open_request(1, "interactive")
    a.charge_tokens(10)
    a.charge_rows(10, 2.0)
    b = book.open_request(2, "interactive")
    b.charge_tokens(30)
    b.charge_rows(30, 2.0)
    book.close_request(1, "done")
    book.close_request(2, "done")
    cell = book.class_rollup()["interactive"]
    # Σ compute / Σ tokens = 4.0/40, not the mean of per-request ratios
    assert cell["cost_per_token_s"] == pytest.approx(0.1)
    assert cell["requests"] == 2 and cell["tokens"] == 40


def test_stall_causes_cover_the_scheduler_parks():
    assert set(STALL_CAUSES) == {"pool_dry", "promo_pending",
                                 "prefill_hold", "queue_wait",
                                 "handoff_wait", "budget_wait"}


# -------------------------------------- conservation under chaos drills

def test_conservation_healthy_replay(make_engine):
    from costcheck import leg_healthy

    _, fails = leg_healthy(_args(), make_engine)
    assert fails == []


def test_conservation_cancel_storm(make_engine):
    """Cancels land mid-prefill, mid-decode and still-queued; every
    cancelled bill must close exactly once and the books still balance
    (zero orphaned, zero duplicated entries)."""
    from costcheck import leg_cancel

    row, fails = leg_cancel(_args(), make_engine)
    assert fails == []
    assert row["cancelled"] > 0


def test_conservation_kill_mid_decode_recovery(make_engine, tmp_path):
    from costcheck import leg_recovery

    row, fails = leg_recovery(_args(), make_engine, str(tmp_path))
    assert fails == []
    assert row["recovered"] > 0 and row["open_at_kill"] > 0


def test_conservation_two_pool_handoff(make_engine):
    """The cross-seam equality: the decode pool's book folds the carried
    prefill-side bills, so decode-book minus prefill-book totals must
    equal the decode engine's own census — and the DCN seam is billed."""
    from costcheck import leg_disagg

    row, fails = leg_disagg(_args(requests=24), make_engine)
    assert fails == []
    assert row["handed_off"] > 0
    assert row["dcn_bytes"] > 0 and row["handoff_wait_s"] > 0


def test_double_count_mutation_breaks_conservation(make_engine):
    from costcheck import leg_healthy

    _, fails = leg_healthy(_args(), make_engine,
                           inject="double-count-dispatch")
    assert any("row-steps" in f for f in fails)


def test_leak_ledger_mutation_trips_open_audit(make_engine):
    from costcheck import leg_healthy

    _, fails = leg_healthy(_args(), make_engine, inject="leak-ledger")
    assert any("still open" in f for f in fails)
