"""Overlap tp scheme (ISSUE 10): ring-decomposed combines + deferred ffn
gather must be BITWISE the fused scheme — latency hiding is a schedule
property, never a numerics change.

The load-bearing identities this file pins:

* the ring's rank-order left fold == XLA's all_reduce/reduce_scatter fold,
  so overlap logits are bit-for-bit fused logits (f32 weights, Q40
  weights, the Q80 wire) across tp in {2, 4, 8};
* the deferred (double-buffered) ffn gather moves WHERE the residual add
  happens, not what it computes — pinned at the scan boundaries (a
  1-layer model exercises first==last; multi-layer exercises the carry);
* the decomposition holds under every cache layout the engine serves:
  contiguous batch, paged, and the speculative K-query verify dispatch;
* constraint errors (sp > 1, ragged ring chunks) fire loudly and early.
"""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

# dims that satisfy every scheme's constraints up to tp=8 with Q40/Q80:
# dim/8 = 32 and hidden/8 = 64 are whole 32-blocks
SPEC = TransformerSpec(dim=256, hidden_dim=512, n_layers=2, n_heads=8,
                       n_kv_heads=8, vocab_size=96, seq_len=16)
SPEC80 = TransformerSpec(**{**SPEC.__dict__,
                            "buffer_float_type": FloatType.Q80})
SPEC_1L = TransformerSpec(**{**SPEC.__dict__, "n_layers": 1})
SPEC_3L = TransformerSpec(**{**SPEC.__dict__, "n_layers": 3})


def _params(spec, seed=11, scale=0.1, q40=False):
    from distributed_llama_tpu.models.synth import synth_params

    return synth_params(spec, q40=q40, seed=seed, scale=scale)


def _forward_logits(spec, p, scheme, tp, tokens, decode_token=3):
    """(prefill logits, decode-T=1 logits) under one scheme on a tp mesh."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    mesh = make_mesh(tp=tp)
    fwd = make_sharded_forward(spec, mesh, scheme=scheme)
    got, cache = fwd(shard_params(p, mesh, scheme=scheme),
                     shard_cache(init_cache(spec), mesh),
                     jnp.asarray(tokens, jnp.int32), jnp.int32(0))
    got2, _ = fwd(shard_params(p, mesh, scheme=scheme), cache,
                  jnp.asarray([decode_token], jnp.int32),
                  jnp.int32(len(tokens)))
    return np.asarray(got), np.asarray(got2)


@pytest.mark.parametrize("tp", [2, 4, 8])
def test_overlap_bitwise_equals_fused_f32(tp):
    """The acceptance identity: f32 decode logits bitwise equal to fused
    (prefill T>1 AND the T=1 decode step), tolerance-equal to ref."""
    p = _params(SPEC)
    tokens = [4, 8, 2, 61]
    fused = _forward_logits(SPEC, p, "fused", tp, tokens)
    over = _forward_logits(SPEC, p, "overlap", tp, tokens)
    ref = _forward_logits(SPEC, p, "ref", tp, tokens)
    np.testing.assert_array_equal(over[0], fused[0])
    np.testing.assert_array_equal(over[1], fused[1])
    np.testing.assert_allclose(over[0], ref[0], rtol=0, atol=2e-5)
    np.testing.assert_allclose(over[1], ref[1], rtol=0, atol=2e-5)


@pytest.mark.parametrize("tp", [2, 4, 8])
def test_overlap_bitwise_equals_fused_q40_weights(tp):
    """Q40 weights: the chunk slicing never touches the quantized input
    blocks (output rows slice freely), so bitwise holds through the
    codec path too."""
    p = _params(SPEC, q40=True, seed=7, scale=0.3)
    fused = _forward_logits(SPEC, p, "fused", tp, [4, 8])
    over = _forward_logits(SPEC, p, "overlap", tp, [4, 8])
    np.testing.assert_array_equal(over[0], fused[0])
    np.testing.assert_array_equal(over[1], fused[1])


@pytest.mark.parametrize("tp", [2, 4])
def test_overlap_q80_wire_bitwise_and_tolerance(tp):
    """The Q80 wire path: the ring's band == psum_scatter's band bitwise,
    so the SAME packed int8+f16 payload crosses the wire and the overlap
    logits equal fused exactly; both stay within the compounded quant
    tolerance of the f32 reference."""
    p = _params(SPEC, seed=31)
    fused = _forward_logits(SPEC80, p, "fused", tp, [4, 8, 61])
    over = _forward_logits(SPEC80, p, "overlap", tp, [4, 8, 61])
    ref32 = _forward_logits(SPEC, p, "ref", tp, [4, 8, 61])
    np.testing.assert_array_equal(over[0], fused[0])
    np.testing.assert_array_equal(over[1], fused[1])
    assert np.abs(over[0] - ref32[0]).max() < 0.15


@pytest.mark.parametrize("spec", [SPEC_1L, SPEC_3L],
                         ids=["one-layer", "three-layer"])
def test_overlap_double_buffer_scan_boundaries(spec):
    """The deferred-gather carry's boundary cases: a 1-layer scan (the
    first layer IS the last — its pending must be consumed after the
    scan, and the dummy layer-(-1) buffer must be select-skipped without
    perturbing x) and a multi-layer scan (mid-carry handoff)."""
    p = _params(spec, seed=5)
    fused = _forward_logits(spec, p, "fused", 2, [4, 8, 2])
    over = _forward_logits(spec, p, "overlap", 2, [4, 8, 2])
    np.testing.assert_array_equal(over[0], fused[0])
    np.testing.assert_array_equal(over[1], fused[1])


def test_overlap_batch_paged_and_verify_bitwise():
    """The other sharded entry points (contiguous batch decode, paged
    decode, speculative K-query verify) under overlap == fused bitwise:
    the combine decomposition rides every layer tail identically."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (init_cache_batch,
                                                    init_cache_paged)
    from distributed_llama_tpu.parallel import (
        make_mesh, make_sharded_forward_batch,
        make_sharded_forward_batch_paged, make_sharded_verify,
        shard_cache_batch, shard_cache_paged, shard_params)

    p = _params(SPEC, seed=13)
    mesh = make_mesh(tp=2)
    B, ps = 2, 4
    toks = jnp.asarray([5, 9], jnp.int32)
    pos = jnp.asarray([0, 0], jnp.int32)

    outs = {}
    for scheme in ("fused", "overlap"):
        sp = shard_params(p, mesh, scheme=scheme)
        fwd = make_sharded_forward_batch(SPEC, mesh, scheme=scheme)
        cache = shard_cache_batch(init_cache_batch(SPEC, B), mesh)
        lg, _ = fwd(sp, cache, toks, pos)

        n_pages = B * (SPEC.seq_len // ps) + 1
        table = jnp.asarray(
            [[1, 2, 3, 4], [5, 6, 7, 8]], jnp.int32)
        fwd_p = make_sharded_forward_batch_paged(SPEC, mesh, ps,
                                                 scheme=scheme)
        cache_p = shard_cache_paged(
            init_cache_paged(SPEC, n_pages, ps), mesh)
        lg_p, _ = fwd_p(sp, cache_p, toks, pos, table)

        fwd_v = make_sharded_verify(SPEC, mesh, ps, scheme=scheme)
        cache_v = shard_cache_paged(
            init_cache_paged(SPEC, n_pages, ps), mesh)
        lg_v, _ = fwd_v(sp, cache_v,
                        jnp.asarray([[5, 7, 9, 2], [9, 1, 4, 6]],
                                    jnp.int32), pos, table)
        outs[scheme] = (np.asarray(lg), np.asarray(lg_p), np.asarray(lg_v))

    for a, b in zip(outs["overlap"], outs["fused"]):
        np.testing.assert_array_equal(a, b)


def test_overlap_rejects_sp_and_every_factory_guards():
    """Constraint errors fire at factory/validate time with the clear
    message, not as a mid-trace shape error. (The ring-chunk width
    dim/tp always divides whenever the head constraint holds — dim =
    n_heads * head_size — so the sp gate is the overlap-specific error a
    user can actually hit; the dim check in validate_sharding is
    defensive.)"""
    from distributed_llama_tpu.parallel import (
        make_mesh, make_sharded_forward, make_sharded_forward_batch)

    with pytest.raises(ValueError, match="sp=1"):
        make_sharded_forward(SPEC, make_mesh(sp=2, tp=2), scheme="overlap")
    with pytest.raises(ValueError, match="sp=1"):
        make_sharded_forward_batch(SPEC, make_mesh(sp=2, tp=2),
                                   scheme="overlap")


def test_overlap_tp1_builds_the_fused_program():
    """At tp=1 there is no wire to hide: the overlap scheme builds the
    fused program (no ring, no pending carry) — same logits, and the
    traced program carries no ppermute."""
    import jax.numpy as jnp

    from distributed_llama_tpu.analysis.jaxpr_contracts import walk_fn_eqns
    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    p = _params(SPEC)
    mesh = make_mesh(tp=1)
    fwd_o = make_sharded_forward(SPEC, mesh, scheme="overlap")
    fwd_f = make_sharded_forward(SPEC, mesh, scheme="fused")
    toks = jnp.asarray([4, 8], jnp.int32)
    a, _ = fwd_o(shard_params(p, mesh, scheme="overlap"),
                 shard_cache(init_cache(SPEC), mesh), toks, jnp.int32(0))
    b, _ = fwd_f(shard_params(p, mesh, scheme="fused"),
                 shard_cache(init_cache(SPEC), mesh), toks, jnp.int32(0))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    eqns = walk_fn_eqns(fwd_o, shard_params(p, mesh, scheme="overlap"),
                        shard_cache(init_cache(SPEC), mesh), toks,
                        jnp.int32(0))
    assert not any(e.primitive.name.startswith("ppermute") for e in eqns)


def test_overlap_rank_sim_runs_the_decomposed_program():
    """shard_sim stand-ins (identity permute, rank-0 index) run the
    overlap rank program on one chip: finite logits, and the traced sim
    carries the same matmul inventory as the fused sim — the ring is
    value movement, not extra matmul work (Plan: the full-width partial
    feeds the ring, so dot shapes are scheme-invariant)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.analysis.jaxpr_contracts import walk_fn_eqns
    from distributed_llama_tpu.parallel import shard_sim

    bands = shard_sim.synth_rank_q40(SPEC, 2, scheme="overlap")
    dev = shard_sim.rank_params_to_device(bands)
    fwd = shard_sim.make_rank_forward(SPEC, 2, scheme="overlap")
    toks = jnp.asarray([3, 11], jnp.int32)
    got, _ = fwd(dev, shard_sim.init_rank_cache(SPEC, 2), toks,
                 jnp.int32(0))
    assert np.isfinite(np.asarray(got)).all()

    def dots(scheme):
        f = shard_sim.make_rank_step(SPEC, 2, scheme=scheme)
        bands2 = shard_sim.synth_rank_q40(SPEC, 2, scheme=scheme)
        from distributed_llama_tpu.ops.linear import dequantize_weight

        dense = {k: (np.asarray(dequantize_weight(v))
                     if hasattr(v, "qs") else v)
                 for k, v in bands2.items()}
        dense = shard_sim.rank_params_to_device(dense)
        return sorted(
            tuple(tuple(v.aval.shape) for v in e.invars)
            for e in walk_fn_eqns(f, dense,
                                  shard_sim.init_rank_cache(SPEC, 2),
                                  toks, jnp.int32(0))
            if e.primitive.name in ("dot_general", "einsum"))

    assert dots("overlap") == dots("fused")


def test_overlap_engine_streams_match_fused(monkeypatch):
    """End to end on a tp=2 mesh: the continuous engine's token streams
    under DLLAMA_TP_SCHEME=overlap equal the fused engine's and the
    single-chip engine's — scheduling, paging, and the deferred-gather
    carry all invisible in outputs."""
    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    p = _params(SPEC, seed=3)
    reqs = [[1, 5, 9, 2], [1, 7], [1, 4, 4]]

    def run(scheme=None, mesh=None):
        if scheme is not None:
            monkeypatch.setenv("DLLAMA_TP_SCHEME", scheme)
        eng = ContinuousEngine(SPEC, p, slots=2, temperature=0.0, topp=0.9,
                               seed=3, mesh=mesh)
        outs, _ = eng.run(reqs, steps=8)
        return outs

    single = run()
    fused = run("fused", make_mesh(tp=2))
    over = run("overlap", make_mesh(tp=2))
    assert over == fused == single


def test_rogue_ppermute_fails_j001_for_serialized_schemes():
    """The any-kind guard extended to the new kind: a ppermute traced in
    a ref/fused forward has NO budget term and must fail J001 loudly —
    never a crash, never a silent pass."""
    from distributed_llama_tpu.analysis.jaxpr_contracts import (
        _collective_kind, _moved_bytes, contract_tp_collectives)
    import jax.numpy as jnp

    # the kind normalizer + ring model speak 'ppermute'
    assert _collective_kind("ppermute") == "ppermute"
    assert _collective_kind("collective_permute") == "ppermute"
    aval = jnp.zeros((4,), jnp.float32)
    assert _moved_bytes("ppermute", aval, 4) == 16

    import jax

    import distributed_llama_tpu.parallel.tp as tp_mod

    def psum_with_rogue_hop(a):
        hopped = jax.lax.ppermute(  # the seeded unmodeled collective
            a, "tp", [(i, (i + 1) % 4) for i in range(4)])
        return tp_mod._ici_psum(a) + 0 * hopped

    # the _ici_* defaults bind at def time, so patch the local-step
    # factory make_sharded_forward looks up by name instead
    orig_mls = tp_mod.make_local_step

    def mls(spec, n_slices, n_sp, **kw):
        kw["psum_fn"] = psum_with_rogue_hop
        return orig_mls(spec, n_slices, n_sp, **kw)

    tp_mod.make_local_step = mls
    try:
        res = contract_tp_collectives(scheme="fused")
    finally:
        tp_mod.make_local_step = orig_mls
    assert not res.ok
    assert "ppermute" in res.detail and "no comm_stats term" in res.detail
