"""HTTP inference server: concurrent clients through the slot pool must get
the same outputs as solo engine runs."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


class _IdTokenizer:
    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"<%d>" % tok


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


@pytest.fixture()
def server(params):
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)
    srv.start()
    yield srv
    srv.stop()


def _post(port, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def test_server_concurrent_matches_solo(server, params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    prompts = ["ab", "x", "hello", "q"]
    tok = _IdTokenizer()
    solo = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                            topp=0.9, seed=99).run(
        [tok.encode(p) for p in prompts], steps=8)[0]

    results: dict[int, dict] = {}

    def client(i):
        results[i] = _post(server.port, {"prompt": prompts[i], "steps": 8})

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    for i in range(len(prompts)):
        assert results[i]["tokens"] == solo[i], (i, results[i])
        assert results[i]["text"] == "".join(
            f"<{t}>" for t in solo[i])


def test_server_per_request_sampling_params(server, params):
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    # a sampled request with explicit seed == engine run with that seed
    eng = ContinuousEngine(SPEC, params, slots=1, temperature=0.0, topp=0.9,
                           seed=0)
    req = Request(tokens=_IdTokenizer().encode("ab"), steps=8,
                  temperature=0.9, topp=0.9, seed=1234)
    eng.submit(req)
    while eng.step_once():
        pass
    got = _post(server.port, {"prompt": "ab", "steps": 8,
                              "temperature": 0.9, "topp": 0.9, "seed": 1234})
    assert got["tokens"] == req.out


def test_server_streaming_matches_solo(server, params):
    """stream:true returns one NDJSON line per token then a done line; the
    token sequence equals the non-streaming response."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    tok = _IdTokenizer()
    solo = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                            topp=0.9, seed=99).run(
        [tok.encode("hello")], steps=8)[0][0]

    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/generate",
        data=json.dumps({"prompt": "hello", "steps": 8,
                         "stream": True}).encode())
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(ln) for ln in r if ln.strip()]
    assert lines[-1]["done"] is True
    toks = [ln["token"] for ln in lines[:-1]]
    assert toks == solo
    assert lines[-1]["text"] == "".join(f"<{t}>" for t in solo)
    assert "".join(ln["piece"] for ln in lines[:-1]) == lines[-1]["text"]


def test_server_streaming_with_admission_prefill(params):
    """The serve default (prefill_chunk on): the prompt-echo burst from
    admission prefill must stream in order, pieces chained correctly."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine
    from distributed_llama_tpu.runtime.server import InferenceServer

    tok = _IdTokenizer()
    solo = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                            topp=0.9, seed=99).run(
        [tok.encode("hello")], steps=8)[0][0]

    srv = InferenceServer(SPEC, params, tok, "127.0.0.1", 0, slots=2,
                          steps=8, temperature=0.0, topp=0.9, seed=5,
                          prefill_chunk=2, quiet=True)
    srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate",
            data=json.dumps({"prompt": "hello", "steps": 8,
                             "stream": True}).encode())
        with urllib.request.urlopen(req, timeout=60) as r:
            lines = [json.loads(ln) for ln in r if ln.strip()]
    finally:
        srv.stop()
    assert [ln["token"] for ln in lines[:-1]] == solo
    assert "".join(ln["piece"] for ln in lines[:-1]) == lines[-1]["text"]


def test_server_stream_disconnect_frees_slot(server):
    """A client that vanishes mid-stream must not keep the slot decoding to
    its full budget: the request gets cancelled and the pool drains."""
    import http.client
    import time

    conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=30)
    conn.request("POST", "/generate",
                 body=json.dumps({"prompt": "hello",
                                  "steps": SPEC.seq_len,
                                  "stream": True}))
    resp = conn.getresponse()
    resp.read(1)  # first bytes arrived: the request is in a slot
    conn.close()  # vanish mid-stream

    deadline = time.time() + 30
    while time.time() < deadline:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/health", timeout=30) as r:
            h = json.loads(r.read())
        if h["active"] == 0 and h["queued"] == 0:
            break
        time.sleep(0.05)
    assert h["active"] == 0 and h["queued"] == 0, h


def test_server_health_paged_kv_block_and_q8(params):
    """/health on a q8-paged server exposes the paged_kv capacity block
    (ISSUE 11) and /metrics carries the kv-quant info + pool-byte
    gauges; generation works end to end over the quantized pool."""
    import urllib.request

    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, page_size=4, kv_pages=24,
                          kv_quant="q8")
    srv.start()
    try:
        out = _post(srv.port, {"prompt": "hello", "steps": 4})
        assert out["tokens"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=30) as r:
            h = json.loads(r.read())
        pk = h["paged_kv"]
        assert pk["kv_quant"] == "q8"
        assert pk["page_size"] == 4 and pk["pages"] == 24
        assert 0 < pk["pages_free"] <= 24
        assert pk["pool_bytes"] > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=30) as r:
            text = r.read().decode()
        assert 'dllama_kv_quant_info{kv_quant="q8"} 1' in text
        assert "dllama_kv_page_pool_bytes" in text
    finally:
        srv.stop()


def test_server_scheduler_failure_returns_500(params):
    """A device-step exception must fail pending requests with a 500, not
    leave clients blocked forever on done.wait()."""
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)

    def boom(*a, **k):
        raise RuntimeError("injected device fault")

    srv.engine._step = boom
    srv.start()
    try:
        _post(srv.port, {"prompt": "ab", "steps": 4})
        assert False, "expected 500"
    except urllib.error.HTTPError as e:
        assert e.code == 500
        assert "injected device fault" in json.loads(e.read())["error"]
    finally:
        srv.stop()


def test_engine_rerun_reproduces_streams(params):
    """run() twice on ONE engine: per-run request indices keep the
    seed + request_index contract, so streams are identical."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    tok = _IdTokenizer()
    reqs = [tok.encode("ab"), tok.encode("x")]
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.9, topp=0.9,
                           seed=21)
    first, _ = eng.run(reqs, steps=8)
    second, _ = eng.run(reqs, steps=8)
    assert first == second


def test_server_many_concurrent_mixed_clients(params):
    """Stress: 16 concurrent clients (streaming and not, mixed per-request
    sampling params) through a 2-slot pool with fused chains — every
    request completes with a consistent, per-seed-deterministic stream and
    the pool drains to idle."""
    import time

    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=6, temperature=0.9, topp=0.9,
                          seed=5, block_steps=3, prefill_chunk=2,
                          quiet=True)
    srv.start()
    results: dict[int, dict] = {}

    def client(i):
        # steps=10 > longest prompt's 6 forced tokens: every client SAMPLES
        # (a budget fully consumed by prompt echo would never exercise the
        # per-request seed); key period 3*5=15 is ODD, so the colliding
        # pair (0, 15) crosses the i%2 transport split
        payload = {"prompt": "ab" * (1 + i % 3), "steps": 10,
                   "seed": 100 + i % 5}
        if i % 2:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/generate",
                data=json.dumps({**payload, "stream": True}).encode())
            with urllib.request.urlopen(req, timeout=120) as r:
                lines = [json.loads(ln) for ln in r if ln.strip()]
            assert "error" not in lines[-1], lines[-1]
            results[i] = {"tokens": [ln["token"] for ln in lines[:-1]],
                          "text": lines[-1]["text"]}
        else:
            results[i] = _post(srv.port, payload)

    threads = [threading.Thread(target=client, args=(i,)) for i in range(16)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert len(results) == 16
        # same (prompt, seed) -> same stream, regardless of transport or
        # scheduling interleave (pair 0/15 compares non-streaming vs
        # streaming)
        by_key: dict = {}
        cross_transport = 0
        for i, r in sorted(results.items()):
            key = (1 + i % 3, i % 5)
            if key in by_key:
                j, prev = by_key[key]
                assert r["tokens"] == prev, (i, j, key)
                cross_transport += (i % 2) != (j % 2)
            by_key[key] = (i, r["tokens"])
        assert cross_transport >= 1  # the claim above is actually tested
        deadline = time.time() + 30
        while time.time() < deadline:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/health", timeout=30) as r:
                h = json.loads(r.read())
            if h["active"] == 0 and h["queued"] == 0:
                break
            time.sleep(0.05)
        assert h["active"] == 0 and h["queued"] == 0, h
    finally:
        srv.stop()


def test_server_stop_with_open_stream_leaves_no_threads(params, tmp_path):
    """stop() with a client mid-stream must wake the blocked handler (its
    q.get would otherwise outlive the server) and JOIN it — no leaked
    threads — while the journal keeps the interrupted request recoverable
    (ISSUE 9 satellite)."""
    import time

    from distributed_llama_tpu.runtime.chaos import ChaosMonkey
    from distributed_llama_tpu.runtime.journal import (RequestJournal,
                                                       load_journal)
    from distributed_llama_tpu.runtime.server import InferenceServer

    before = set(threading.enumerate())
    jpath = str(tmp_path / "j.journal")
    srv = InferenceServer(
        SPEC, params, _IdTokenizer(), "127.0.0.1", 0, slots=2, steps=8,
        temperature=0.0, topp=0.9, seed=5, quiet=True,
        journal=RequestJournal(jpath), page_size=4, kv_pages=24,
        # slow every dispatch so the stream is reliably OPEN at stop()
        chaos=ChaosMonkey(step_delay_every=1, step_delay_s=0.2))
    srv.start()
    got: dict = {}

    def client():
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate",
            data=json.dumps({"prompt": "hello", "steps": 8,
                             "stream": True}).encode())
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                got["lines"] = [json.loads(ln) for ln in r if ln.strip()]
        except Exception as e:  # noqa: BLE001 - surfaced in the asserts
            got["error"] = e

    t = threading.Thread(target=client)
    t.start()
    deadline = time.time() + 30
    while time.time() < deadline and not srv._streams:
        time.sleep(0.01)
    assert srv._streams, "stream handler never registered"
    srv.stop()
    t.join(timeout=30)
    assert not t.is_alive()
    # the stream ended with the suspend error, not a hang or a crash
    if "lines" in got:
        assert got["lines"][-1].get("error")
    # every server-owned thread is joined: scheduler, listener, handlers
    deadline = time.time() + 10
    while time.time() < deadline:
        leaked = [th for th in set(threading.enumerate()) - before
                  if th.is_alive() and th is not t]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, leaked
    assert srv.health.state == "stopped"
    # the interrupted request survived in the journal (no retire record)
    assert len([e for e in load_journal(jpath) if e.status is None]) == 1


def test_server_drain_journals_remainder_and_refuses_admission(params,
                                                               tmp_path):
    """The graceful-drain contract (ISSUE 9): draining refuses new work
    with a retryable 503, in-flight requests get the drain budget, and
    whatever remains is journaled — recoverable, pages audited clean."""
    import time

    from distributed_llama_tpu.runtime.chaos import ChaosMonkey
    from distributed_llama_tpu.runtime.journal import (RequestJournal,
                                                       load_journal)
    from distributed_llama_tpu.runtime.server import InferenceServer

    jpath = str(tmp_path / "j.journal")
    srv = InferenceServer(
        SPEC, params, _IdTokenizer(), "127.0.0.1", 0, slots=2, steps=8,
        temperature=0.0, topp=0.9, seed=5, quiet=True,
        journal=RequestJournal(jpath), page_size=4, kv_pages=24,
        chaos=ChaosMonkey(step_delay_every=1, step_delay_s=0.2))
    srv.start()
    got: dict = {}

    def client():
        try:
            got["resp"] = _post(srv.port, {"prompt": "hello", "steps": 8})
        except urllib.error.HTTPError as e:
            got["code"] = e.code
    t = threading.Thread(target=client)
    t.start()
    deadline = time.time() + 30
    while time.time() < deadline:
        with srv.engine._lock:
            queued = len(srv.engine._queue)
        if queued or any(not s.free for s in srv.engine._pool):
            break
        time.sleep(0.01)
    remainder = srv.drain(budget_s=0.05)  # budget far below the request
    assert remainder == 1
    t.join(timeout=30)
    assert got.get("code") == 500  # waiter woken with the suspend error
    assert srv.health.state == "stopped"
    assert srv.engine.audit_pages() == []
    # the journaled remainder is live (no retire record): the next
    # process recovers it
    assert len([e for e in load_journal(jpath) if e.status is None]) == 1


def test_server_drain_finishes_fast_work_without_journaling(params):
    """A drain whose in-flight work completes within the budget journals
    NOTHING and reports zero remainder — the healthy-shutdown path."""
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=4, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)
    srv.start()
    resp = _post(srv.port, {"prompt": "ab", "steps": 4})
    assert resp["tokens"]
    assert srv.drain(budget_s=10.0) == 0
    assert srv.health.state == "stopped"
    # draining a stopped server is a no-op, not an error
    assert srv.drain() == 0


def test_server_draining_refuses_new_requests_with_503(params):
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=4, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)
    srv.start()
    try:
        srv.health.to("draining")
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.port, {"prompt": "ab", "steps": 4})
        assert ei.value.code == 503
        assert "retry" in json.loads(ei.value.read())["error"]
    finally:
        srv.stop()


def test_server_health_and_errors(server):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/health", timeout=30) as r:
        h = json.loads(r.read())
    assert h["slots"] == 2 and h["active"] == 0

    for payload, msg in (({"steps": 0}, "steps"),
                         ({"steps": SPEC.seq_len + 1}, "steps"),
                         ({"prompt": 7}, "prompt"),
                         ({"steps": [1]}, ""),          # TypeError -> 400
                         ({"temperature": {}}, "")):
        try:
            _post(server.port, payload)
            assert False, f"expected 400 for {payload}"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert msg in json.loads(e.read())["error"]


def test_health_kv_tiers_block(params, tmp_path):
    """ISSUE 12: a tiered server surfaces the tier hierarchy in /health
    — per-tier page counts, promotion/demotion flow, and the
    prefill-savings-by-tier attribution (the metrics series' JSON twin);
    untiered servers omit the block."""
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, page_size=4, kv_pages=8,
                          kv_host_pages=4,
                          kv_disk_dir=str(tmp_path / "kv"))
    srv.start()
    try:
        _post(srv.port, {"prompt": "hello tier"})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=30) as r:
            payload = json.loads(r.read())
        tiers = payload["kv_tiers"]
        assert set(tiers["pages"]) == {"hbm", "host", "disk"}
        assert tiers["host_capacity"] == 4
        assert "promotions" in tiers and "demotions" in tiers
        assert set(tiers["prefill_tokens_saved_by_tier"]) == {
            "hbm", "host", "disk"}
    finally:
        srv.stop()


def test_health_omits_kv_tiers_when_untiered(server):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/health", timeout=30) as r:
        payload = json.loads(r.read())
    assert "kv_tiers" not in payload


def test_health_sched_block_and_debug_sched(server):
    """ISSUE 16: after served traffic, /health carries the accounting
    plane's "sched" block (census totals + ledger counts + cost columns)
    and GET /debug/sched exports the dispatch census ring as JSON and
    NDJSON, conservation holding between the two surfaces."""
    _post(server.port, {"prompt": "bill me", "steps": 6})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/health", timeout=30) as r:
        health = json.loads(r.read())
    sched = health["sched"]
    census = sched["census"]
    assert census["dispatches"] > 0
    assert census["tokens"]["decode"] > 0
    assert sched["ledgers"]["open"] == 0
    assert sched["ledgers"]["closed"] >= 1
    totals = sched["cost_totals"]
    assert totals["tokens"] == (census["tokens"]["decode"]
                                + census["tokens"]["prefill"])
    assert totals["decode_row_steps"] == census["row_steps"]
    assert "default" in sched["cost_by_class"]
    assert sched["cost_by_class"]["default"]["cost_per_token_s"] > 0.0

    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug/sched?n=8",
            timeout=30) as r:
        doc = json.loads(r.read())
    assert doc["kind"] == "dllama-sched-census"
    assert doc["totals"] == census
    assert 0 < len(doc["ring"]) <= 8
    assert doc["cost_totals"]["tokens"] == totals["tokens"]
    assert doc["open_ledgers"] == []

    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug/sched?format=ndjson",
            timeout=30) as r:
        assert r.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(ln) for ln in r if ln.strip()]
    assert lines and all("kind" in ln for ln in lines)

    try:
        urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug/sched?n=zap",
            timeout=30)
        assert False, "expected 400 for a non-integer tail"
    except urllib.error.HTTPError as e:
        assert e.code == 400


def test_stream_registering_during_stop_is_still_joined(params):
    """The _streams register/join TOCTOU (ISSUE 17 satellite): a handler
    thread that registers AFTER stop() snapshots the registry must still
    be joined before stop() returns. An early handler (registered before
    stop) spawns and registers a late one only once stop() is already
    inside its join loop — with a single-snapshot join the late thread
    would outlive the server."""
    import time as _time

    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=4, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)
    srv.start()
    state = {}

    def late_handler():
        with srv._streams_lock:
            srv._streams.add(threading.current_thread())
        try:
            _time.sleep(0.25)  # outlive a single-snapshot stop()
        finally:
            with srv._streams_lock:
                srv._streams.discard(threading.current_thread())

    def early_handler():
        with srv._streams_lock:
            srv._streams.add(threading.current_thread())
        try:
            # wait until stop() is underway: it must join THIS thread,
            # so everything below happens inside its join loop
            assert srv._stopped.wait(10)
            _time.sleep(0.05)
            late = threading.Thread(target=late_handler, daemon=True)
            late.start()
            state["late"] = late
        finally:
            with srv._streams_lock:
                srv._streams.discard(threading.current_thread())

    early = threading.Thread(target=early_handler, daemon=True)
    early.start()
    deadline = _time.time() + 5
    while _time.time() < deadline and early not in srv._streams:
        _time.sleep(0.005)
    assert early in srv._streams, "early handler never registered"

    srv.stop()
    assert not early.is_alive(), "early stream handler was not joined"
    assert "late" in state, "late handler never spawned"
    assert not state["late"].is_alive(), \
        "handler registering during stop()'s join was NOT joined — " \
        "the register/join TOCTOU is back"


# ------------------------------------------------------- watchtower plane


def _get_json(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return json.loads(r.read())


def test_health_watch_block_and_debug_incidents(server):
    """ISSUE 20: /health carries the watchtower heartbeat, and
    /debug/incidents serves the detection plane — even before any
    periodic loop ran a tick (watch_interval_s=0: manual ticks)."""
    h = _get_json(server.port, "/health")
    assert h["schema"] == 3
    watch = h["watch"]
    assert watch["incidents_total"] == 0
    assert watch["last_incident"] is None
    assert set(watch["detectors"]) == set(
        __import__("distributed_llama_tpu.obs.watch",
                   fromlist=["KINDS"]).KINDS)
    # a manual tick scrapes the server's OWN health payload + registry
    assert server.watch_tick() == []
    assert _get_json(server.port, "/health")["watch"]["ticks"] == 1

    doc = _get_json(server.port, "/debug/incidents")
    assert doc["incidents_total"] == 0
    assert doc["incident_log"] == []
    assert doc["ring"]["replicas"]["self"]["ticks"] == 1
    row = doc["ring"]["replicas"]["self"]["rows"][0]
    assert row["tick"] == 0 and row["kv_pages_free"] >= 0

    # ndjson stream: one line per incident (none yet — empty body)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/debug/incidents"
            f"?format=ndjson", timeout=30) as r:
        assert r.headers["Content-Type"].startswith(
            "application/x-ndjson")
        assert r.read() == b""

    # junk ?n is a 400, not a 500
    try:
        _get_json(server.port, "/debug/incidents?n=junk")
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400

    # detector states ride /metrics
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=30) as r:
        text = r.read().decode()
    assert 'dllama_detector_state{kind="slo_burn"} 0' in text
    assert 'dllama_incidents_total{kind="page_leak"} 0' in text


def test_server_incident_dumps_flightrec_bundle(params, tmp_path):
    """A detector transitioning into firing must leave a flight-recorder
    bundle behind with reason="incident" and the detector kind stamped
    in the header — the auto-forensics half of the tentpole."""
    from distributed_llama_tpu.obs.flightrec import load_bundle
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True,
                          flightrec_dir=str(tmp_path))
    srv.start()
    try:
        # hair-trigger the recovery detector and feed it a storm by
        # hand — the wiring under test is observe -> _on_incident ->
        # _flightrec_dump, not the detector math (test_watch owns that)
        srv._watch.thresholds["recovery_storm_min"] = 1
        from distributed_llama_tpu.obs.watch import blank_sample

        fired = []
        for n in (1, 2):
            s = blank_sample()
            s["recoveries"] = n
            fired += srv._watch.observe("self", s)
        assert [i.kind for i in fired] == ["recovery_storm"]
        bundles = [p.name for p in tmp_path.iterdir()
                   if p.name.startswith("flightrec-incident-")]
        assert len(bundles) == 1
        bundle = load_bundle(str(tmp_path / bundles[0]))
        assert bundle["reason"] == "incident"
        assert bundle["incident_kind"] == "recovery_storm"
        # the incident is on /debug/incidents and in /health
        doc = _get_json(srv.port, "/debug/incidents?kind=recovery_storm")
        assert doc["incident_log"][0]["kind"] == "recovery_storm"
        assert doc["incident_log"][0]["evidence"]
        h = _get_json(srv.port, "/health")
        assert h["watch"]["incidents_total"] == 1
        assert h["watch"]["last_incident"]["kind"] == "recovery_storm"
    finally:
        srv.stop()


def test_server_watch_loop_ticks_periodically(params):
    """watch_interval_s > 0 starts the supervisor loop; ticks accrue
    without any client traffic, and stop() parks the loop."""
    import time as _time

    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, watch_interval_s=0.05)
    srv.start()
    try:
        deadline = _time.time() + 10
        while _time.time() < deadline \
                and srv._watch.ring.rows_total < 2:
            _time.sleep(0.02)
        assert srv._watch.ring.rows_total >= 2
    finally:
        srv.stop()
    assert srv._watch_stop.is_set()
