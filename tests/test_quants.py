"""Quantization codec tests.

Mirrors the reference's quants-test.cpp: seeded xorshift input (seed 800000010),
Q80 round-trip tolerance 0.0043 per element across lengths {1024, 768, 2752}
(reference src/quants-test.cpp:7-51), plus Q40 round-trip, wire-format
pack/unpack identity, and an independent struct-level cross-check of the Q40
encoder against the converter algorithm.
"""

import struct

import numpy as np
import pytest

from distributed_llama_tpu.ops import quants as q
from distributed_llama_tpu.utils.rng import Xorshift64

LENGTHS = [1024, 768, 2752]


def _seeded(n, seed=800000010):
    return Xorshift64(seed).f32_array(n)


@pytest.mark.parametrize("n", LENGTHS)
def test_q80_roundtrip_tolerance(n):
    x = _seeded(n)
    qs, d = q.quantize_q80(x)
    y = q.dequantize_q80(qs, d)
    assert np.max(np.abs(x - y)) <= 0.0043  # reference quants-test.cpp:26


@pytest.mark.parametrize("n", LENGTHS)
def test_q40_roundtrip_tolerance(n):
    x = _seeded(n) - 0.5  # exercise signed values
    qs, d = q.quantize_q40(x)
    y = q.dequantize_q40(qs, d)
    # worst case: delta = amax/8 <= 0.0625; the clamp-15 end loses a full delta
    assert np.max(np.abs(x - y)) <= 0.5 / 8 * 1.02 + 1e-3


def test_q40_wire_roundtrip():
    x = _seeded(64 * 32).reshape(64, 32 * 1) - 0.25
    x = x.reshape(8, 256)
    qs, d = q.quantize_q40(x)
    buf = q.pack_q40_bytes(qs, d)
    assert len(buf) == q.batch_bytes(q.FloatType.Q40, 256, 8)
    qs2, d2 = q.unpack_q40_bytes(buf, (8, 256))
    assert np.array_equal(qs, qs2)
    assert np.array_equal(d.view(np.uint16), d2.view(np.uint16))


def test_q80_wire_roundtrip():
    x = _seeded(4 * 320).reshape(4, 320) - 0.5
    qs, d = q.quantize_q80(x)
    buf = q.pack_q80_bytes(qs, d)
    assert len(buf) == q.batch_bytes(q.FloatType.Q80, 320, 4)
    qs2, d2 = q.unpack_q80_bytes(buf, (4, 320))
    assert np.array_equal(qs, qs2)
    assert np.array_equal(d.view(np.uint16), d2.view(np.uint16))


def test_q40_encoder_matches_scalar_algorithm():
    """Cross-check vectorized encoder vs a direct scalar transcription of the
    converter algorithm (converter.py:13-43 semantics, written independently)."""
    x = (_seeded(3 * 32) - 0.5).astype(np.float32)
    qs, d16 = q.quantize_q40(x)
    groups = x.reshape(-1, 32)
    out = b""
    for g in groups:
        gmax, gmin = g.max(), g.min()
        delta = np.float32((gmin if -gmin > gmax else gmax) / np.float32(-8.0))
        d = np.float16(delta)
        inv = np.float32(0.0) if delta == 0 else np.float32(1.0) / delta
        codes = [min(int(v * inv + np.float32(8.5)), 15) for v in g]
        packed = bytes((codes[i] & 0xF) | ((codes[i + 16] & 0xF) << 4)
                       for i in range(16))
        out += struct.pack("<e", d) + packed
    assert q.pack_q40_bytes(qs, d16) == out


def test_q40_decode_value_map():
    """Nibble j low -> value j, high -> value j+16; (code-8)*delta."""
    d16 = np.array([[np.float16(2.0)]], dtype=np.float16)  # (1 row, 1 block)
    qs = np.zeros((1, 1, 16), dtype=np.uint8)
    qs[0, 0, 0] = 0x0F | (0x00 << 4)  # value0 code=15, value16 code=0
    y = q.dequantize_q40(qs, d16)
    assert y.shape == (1, 32)
    assert y[0, 0] == (15 - 8) * 2.0
    assert y[0, 16] == (0 - 8) * 2.0
    assert y[0, 1] == (0 - 8) * 2.0


def test_batch_bytes_parity():
    # sizes from the reference's getBatchBytes for known models
    assert q.batch_bytes(q.FloatType.F32, 4096, 4096) == 4096 * 4096 * 4
    assert q.batch_bytes(q.FloatType.Q40, 4096, 4096) == 4096 * 4096 // 32 * 18
    assert q.batch_bytes(q.FloatType.Q80, 4096) == 4096 // 32 * 34


def test_jax_codecs_match_numpy():
    import jax.numpy as jnp

    x = (_seeded(2 * 128).reshape(2, 128) - 0.5).astype(np.float32)
    qs, d = q.quantize_q80(x)
    qsj, dj = q.quantize_q80_jax(jnp.asarray(x))
    assert np.array_equal(np.asarray(qsj), qs)
    assert np.array_equal(np.asarray(dj).view(np.uint16), d.view(np.uint16))
    y = q.dequantize_q80(qs, d)
    yj = q.dequantize_q80_jax(jnp.asarray(qs), jnp.asarray(d))
    np.testing.assert_allclose(np.asarray(yj), y, rtol=0, atol=0)

    qs4, d4 = q.quantize_q40(x)
    y4 = q.dequantize_q40(qs4, d4)
    y4j = q.dequantize_q40_jax(jnp.asarray(qs4), jnp.asarray(d4))
    np.testing.assert_allclose(np.asarray(y4j), y4, rtol=0, atol=0)
