"""Watchtower detection plane (ISSUE 20): ring delta math, the detector
suite's golden windows, hysteresis, and incident forensics."""

import json

import pytest

from distributed_llama_tpu.obs.watch import (COLUMNS, DETECTORS, KINDS,
                                             THRESHOLDS, Incident,
                                             SignalRing, Watchtower,
                                             _DetectorState, blank_sample,
                                             detect_goodput_collapse,
                                             detect_handoff_spike,
                                             detect_page_leak,
                                             detect_recovery_storm,
                                             detect_slo_burn,
                                             detect_spec_collapse,
                                             detect_stall_shift,
                                             sample_from_signals)


def _rows(n, **kw):
    """n golden ring rows: every column zero except the overrides
    (a scalar sets every row; a list/tuple sets row-by-row)."""
    out = []
    for i in range(n):
        row = {c: 0 for c in COLUMNS}
        row["tick"] = i
        for col, v in kw.items():
            assert col in COLUMNS, col
            row[col] = v[i] if isinstance(v, (list, tuple)) else v
        out.append(row)
    return out


# ---------------------------------------------------------------- the ring


def test_signal_ring_deltas_gauges_and_reset_clamp():
    ring = SignalRing(keep=8)
    s = blank_sample()
    s.update(kv_pages_free=10, met=3, goodput_tokens=20)
    r0 = ring.observe("a", s)
    # first tick: gauges copied, counter deltas ARE the absolutes
    assert r0["tick"] == 0 and r0["kv_pages_free"] == 10
    assert r0["met"] == 3 and r0["goodput_tokens"] == 20
    s.update(kv_pages_free=7, met=5, goodput_tokens=20)
    r1 = ring.observe("a", s)
    assert r1["tick"] == 1 and r1["kv_pages_free"] == 7
    assert r1["met"] == 2 and r1["goodput_tokens"] == 0
    # a counter moving BACKWARDS is a replica restart: the delta clamps
    # at zero (Prometheus reset semantics), it never goes negative
    s.update(met=1)
    assert ring.observe("a", s)["met"] == 0
    assert ring.ticks("a") == 3 and ring.rows_total == 3
    # replicas are independent streams
    ring.observe("b", blank_sample())
    assert ring.replicas() == ["a", "b"]
    assert ring.ticks("b") == 1
    assert len(ring.window("a")) == 3


def test_signal_ring_bounded_and_byte_identical():
    def feed(ring):
        for i in range(20):
            s = blank_sample()
            s.update(met=i, kv_pages_free=20 - i, queue_depth=i % 3)
            ring.observe("r", s)
        return ring

    a = feed(SignalRing(keep=8))
    b = feed(SignalRing(keep=8))
    assert len(a.window("r")) == 8  # bounded
    assert (json.dumps(a.to_json(), sort_keys=True)
            == json.dumps(b.to_json(), sort_keys=True))


# ----------------------------------------------- detector golden windows


def test_slo_burn_needs_both_windows():
    t = THRESHOLDS
    # both windows burning -> hot
    hot, note = detect_slo_burn(_rows(10, violated=1, met=1), t)
    assert hot and "bad" in note
    # fast-only burn (ancient history clean) -> quiet: the slow window
    # dilutes below its fraction
    rows = _rows(60, met=1) + _rows(5, violated=2, met=0)
    assert not detect_slo_burn(rows, t)[0]
    # slow-window burn but a clean fast window -> quiet (recovered)
    rows = _rows(55, violated=1) + _rows(5, met=2)
    assert not detect_slo_burn(rows, t)[0]
    # too few verdicts to mean anything -> quiet
    assert not detect_slo_burn(_rows(3, violated=1), t)[0]


def test_page_leak_wants_monotone_idle_decline_without_demotions():
    t = THRESHOLDS
    frees = [20, 19, 19, 18, 17, 17, 16, 16, 15, 15, 14, 14]
    hot, note = detect_page_leak(_rows(12, kv_pages_free=frees), t)
    assert hot and "idle pages_free 20->14" in note
    # demotions in the window explain the decline -> quiet
    assert not detect_page_leak(
        _rows(12, kv_pages_free=frees, demotions=1), t)[0]
    # non-monotone (pages come back) -> churn, not a leak
    bouncy = [20, 18, 20, 17, 20, 16, 20, 15, 20, 14, 20, 13]
    assert not detect_page_leak(_rows(12, kv_pages_free=bouncy), t)[0]
    # busy rows are not evidence (in-flight requests hold pages)
    assert not detect_page_leak(
        _rows(12, kv_pages_free=frees, active=1), t)[0]


def test_stall_shift_fires_on_dominant_cause_change():
    t = THRESHOLDS
    rows = (_rows(15, stall_queue_wait=2)
            + _rows(5, stall_pool_dry=3))
    hot, note = detect_stall_shift(rows, t)
    assert hot and "queue_wait" in note and "pool_dry" in note
    # same dominant cause throughout -> quiet
    assert not detect_stall_shift(_rows(20, stall_queue_wait=2), t)[0]
    # mass under the floor -> quiet (noise, not a regime)
    tiny = _rows(15, stall_queue_wait=1) + _rows(5, stall_pool_dry=1)
    assert not detect_stall_shift(tiny, t)[0]
    assert not detect_stall_shift(_rows(4), t)[0]  # window not filled


def test_goodput_collapse_needs_completions_not_mere_demand():
    t = THRESHOLDS
    rows = (_rows(12, goodput_tokens=2, met=1)
            + _rows(6, violated=1, queue_depth=2))
    assert detect_goodput_collapse(rows, t)[0]
    # demand with NO completions is a long decode stretch, not collapse
    rows = (_rows(12, goodput_tokens=2, met=1)
            + _rows(6, queue_depth=2, active=2))
    assert not detect_goodput_collapse(rows, t)[0]
    # a base window that never produced proves nothing
    rows = _rows(12) + _rows(6, violated=1)
    assert not detect_goodput_collapse(rows, t)[0]


def test_spec_recovery_handoff_detectors():
    t = THRESHOLDS
    assert detect_spec_collapse(
        _rows(8, spec_proposed=3, spec_accepted=0), t)[0]
    assert not detect_spec_collapse(
        _rows(8, spec_proposed=3, spec_accepted=2), t)[0]
    assert detect_recovery_storm(_rows(10, recoveries=[1, 0, 1, 0, 1,
                                                       0, 0, 0, 0, 0]),
                                 t)[0]
    assert not detect_recovery_storm(
        _rows(10, recoveries=[1, 0, 0, 0, 0, 0, 0, 0, 0, 1]), t)[0]
    assert detect_handoff_spike(
        _rows(10, handoff_total=1, handoff_failed=[0, 1, 1, 0, 1, 1,
                                                   0, 1, 0, 0]), t)[0]
    assert not detect_handoff_spike(
        _rows(10, handoff_total=1, handoff_failed=[0, 0, 1, 0, 0, 0,
                                                   0, 0, 0, 0]), t)[0]


# -------------------------------------------------------------- hysteresis


def test_hysteresis_state_machine():
    st = _DetectorState()
    # one hot tick only warms (warm=2): no incident yet
    assert st.advance(True, 2, 3, tick=0) is False
    assert st.state == "warming"
    # a quiet tick resets warming — a single noisy tick never fires
    assert st.advance(False, 2, 3, tick=1) is False
    assert st.state == "ok"
    # two consecutive hot ticks fire EXACTLY once
    assert st.advance(True, 2, 3, tick=2) is False
    assert st.advance(True, 2, 3, tick=3) is True
    assert st.state == "firing"
    assert st.advance(True, 2, 3, tick=4) is False  # still firing
    # quiet ticks cool; re-heating mid-cool returns to firing WITHOUT
    # a new incident
    assert st.advance(False, 2, 3, tick=5) is False
    assert st.state == "cooling"
    assert st.advance(True, 2, 3, tick=6) is False
    assert st.state == "firing"
    # cool ticks in a row close it out
    for tick in (7, 8, 9):
        assert st.advance(False, 2, 3, tick=tick) is False
    assert st.state == "ok"


# ------------------------------------------------------------- watchtower


def _storm_sample(recoveries):
    s = blank_sample()
    s["recoveries"] = recoveries
    return s


def test_watchtower_fires_once_with_evidence_and_metrics():
    from distributed_llama_tpu.obs.metrics import Registry

    seen = []
    reg = Registry()
    tower = Watchtower(registry=reg, on_incident=seen.append)
    total = 0
    for _ in range(6):
        total += 1
        tower.observe("r0", _storm_sample(total))
    assert tower.incidents_total == 1  # firing is an edge, not a level
    assert seen and seen[0].kind == "recovery_storm"
    inc = tower.incidents(kind="recovery_storm")[-1]
    assert isinstance(inc, Incident) and inc.replica == "r0"
    assert inc.evidence and inc.evidence[-1]["recoveries"] == 1
    assert tower.by_kind()["recovery_storm"] == 1
    snap = tower.snapshot()
    assert snap["incidents_total"] == 1
    assert snap["last_incident"]["kind"] == "recovery_storm"
    assert snap["detectors"]["recovery_storm"] == "firing"
    assert set(snap["detectors"]) == set(KINDS)
    text = reg.expose()
    assert 'dllama_incidents_total{kind="recovery_storm"} 1' in text
    assert 'dllama_detector_state{kind="recovery_storm"} 2' in text
    full = tower.to_json(tail=4)
    assert full["incidents_by_replica"] == {"r0": 1}
    assert len(full["ring"]["replicas"]["r0"]["rows"]) == 4


def test_watchtower_mute_and_threshold_overrides():
    muted = Watchtower(mute=("recovery_storm",))
    eager = Watchtower(thresholds={"recovery_storm_min": 1})
    for t in range(1, 7):
        muted.observe("r", _storm_sample(t))
        eager.observe("r", _storm_sample(t))
    assert muted.incidents_total == 0
    assert eager.incidents_total == 1
    assert eager.thresholds["recovery_storm_min"] == 1
    assert THRESHOLDS["recovery_storm_min"] == 3  # base table untouched


def test_watchtower_byte_identical_across_runs():
    def run():
        tower = Watchtower()
        total = 0
        for i in range(30):
            total += (1 if i % 3 == 0 else 0)
            s = _storm_sample(total)
            s["kv_pages_free"] = 20 - i % 5
            tower.observe("a", s)
            tower.observe("b", blank_sample())
        return json.dumps(tower.to_json(), sort_keys=True)

    assert run() == run()


def test_detector_registry_is_consistent():
    assert KINDS == tuple(d.kind for d in DETECTORS)
    assert len(set(KINDS)) == len(KINDS)
    for det in DETECTORS:
        assert det.warm >= 1 and det.cool >= 1 and det.window >= 1


# ---------------------------------------------------------- live sampling


def test_sample_from_signals_maps_row_and_metrics():
    from distributed_llama_tpu.obs.fleet import ReplicaSignals

    row = ReplicaSignals(name="r", kv_pages_free=5, queue_depth=2,
                         active=1, generated_tokens=40,
                         goodput_tokens=30,
                         slo={"interactive": {"met": 3, "violated": 1,
                                              "failed": 0,
                                              "goodput_tokens": 30}},
                         stall_seconds={"pool_dry": 0.25})
    samples = {
        "dllama_recoveries_total": 2.0,
        'dllama_handoff_requests_total{verdict="ok"} ': 0,  # ignored
        'dllama_handoff_requests_total{verdict="ok"}': 3.0,
        'dllama_handoff_requests_total{verdict="failed"}': 1.0,
        'dllama_tier_demotions_total{dir="down"}': 4.0,
    }
    s = sample_from_signals(row, samples)
    assert s["kv_pages_free"] == 5 and s["queue_depth"] == 2
    assert s["met"] == 3 and s["violated"] == 1
    assert s["goodput_tokens"] == 30 and s["generated_tokens"] == 40
    assert s["stall_pool_dry"] == 250  # seconds -> integer ms
    assert s["recoveries"] == 2
    assert s["handoff_total"] == 4 and s["handoff_failed"] == 1
    assert s["demotions"] == 4
    # a bare row + no scrape degrades to zeros, not a crash
    zeros = sample_from_signals(ReplicaSignals(name="x"))
    assert all(v == 0 for v in zeros.values())


def test_sample_column_contract():
    """Every sample builder emits exactly the ring's columns — a new
    detector column must be added to COLUMNS or it silently reads 0."""
    s = blank_sample()
    assert set(s) | {"tick"} == set(COLUMNS)
    with pytest.raises(AssertionError):
        _rows(1, not_a_column=1)
