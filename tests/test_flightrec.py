"""Flight recorder: ring, bundle schema, dump triggers, tracecheck load
(ISSUE 15)."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

from distributed_llama_tpu.models.spec import TransformerSpec  # noqa: E402
from distributed_llama_tpu.models.synth import synth_params  # noqa: E402
from distributed_llama_tpu.obs.flightrec import (FlightRecorder,  # noqa: E402
                                                 is_bundle_file,
                                                 load_bundle,
                                                 validate_bundle)
from distributed_llama_tpu.obs.metrics import Registry  # noqa: E402
from distributed_llama_tpu.obs.spans import SpanTracer  # noqa: E402

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


class _IdTokenizer:
    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"<%d>" % tok


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


def test_ring_bounds_and_bundle_roundtrip(tmp_path):
    reg = Registry()
    reg.counter("dllama_demo_total", "demo").inc(3)
    spans = SpanTracer()
    with spans.span("step", cat="decode", active=1):
        pass
    jpath = tmp_path / "j.ndjson"
    jpath.write_text('{"t":"journal","v":1}\n'
                     '{"t":"admit","id":0,"tokens":[1],"steps":2,'
                     '"temperature":0.0,"topp":0.9,"seed":1,"slo":null,'
                     '"cursor":0}\n')
    rec = FlightRecorder(capacity=4, registry=reg, spans=spans,
                         journal_path=str(jpath),
                         config={"dim": 64}, tail_lines=8)
    for i in range(10):
        rec.note(f"event{i}", n=i)
    path = rec.dump(str(tmp_path / "out"), "watchdog")
    bundle = load_bundle(path)  # load validates
    # the ring kept only the last 4 events
    assert [e["event"] for e in bundle["events"]] == \
        ["event6", "event7", "event8", "event9"]
    assert bundle["reason"] == "watchdog"
    assert bundle["config"] == {"dim": 64}
    assert "dllama_demo_total 3" in bundle["metrics"]
    assert bundle["spans"][0]["span"] == "step"
    assert bundle["spans_dropped"] == 0
    assert len(bundle["journal_tail"]) == 2
    assert json.loads(bundle["journal_tail"][0])["t"] == "journal"
    assert is_bundle_file(path)
    # repeated dumps never clobber (sequence-named)
    path2 = rec.dump(str(tmp_path / "out"), "watchdog")
    assert path2 != path and os.path.exists(path) and os.path.exists(path2)
    # explicit .json target is honored verbatim
    explicit = str(tmp_path / "bundle.json")
    assert rec.dump(explicit, "sigterm_drain") == explicit
    assert load_bundle(explicit)["reason"] == "sigterm_drain"


def test_bundle_without_bindings_still_valid(tmp_path):
    """The supervisor's vantage: no registry, no spans, no journal — the
    bundle is still schema-clean (empty sections, never missing ones)."""
    rec = FlightRecorder()
    rec.note("supervisor.crash", rc=1)
    path = rec.dump(str(tmp_path), "crash_loop")
    bundle = load_bundle(path)
    assert bundle["spans"] == [] and bundle["journal_tail"] == []
    assert bundle["metrics"] == ""
    assert bundle["events"][0]["event"] == "supervisor.crash"


@pytest.mark.parametrize("mutate", [
    lambda b: b.pop("reason"),
    lambda b: b.update(kind="nope"),
    lambda b: b.update(version=99),
    lambda b: b.update(events="not-a-list"),
    lambda b: b.update(spans=[{"nope": 1}]),
    lambda b: b.update(metrics=None),
    lambda b: b.pop("spans_dropped"),
])
def test_validate_rejects_damage(tmp_path, mutate):
    rec = FlightRecorder()
    bundle = rec.snapshot_bundle("watchdog")
    validate_bundle(bundle)  # sane before mutation
    mutate(bundle)
    with pytest.raises(ValueError):
        validate_bundle(bundle)


def test_tracecheck_validates_and_rejects_bundles(tmp_path):
    """The CI hook: tools/tracecheck.py accepts a good bundle (exit 0)
    and flags a damaged one (exit 1 — not the usage-error 2 a naive
    non-zero check would vacuously pass on)."""
    import tracecheck

    rec = FlightRecorder()
    rec.note("watchdog", elapsed_s=0.5)
    path = rec.dump(str(tmp_path), "watchdog")
    assert tracecheck.main([path, "--json"]) == 0
    bundle = json.load(open(path))
    del bundle["events"]
    broken = str(tmp_path / "broken.json")
    with open(broken, "w") as fh:
        json.dump(bundle, fh)
    assert tracecheck.main([broken]) == 1


def test_watchdog_trip_dumps_bundle_from_server(params, tmp_path):
    """The wired trigger: a server whose watchdog fires writes a bundle
    into --flightrec DIR; the SIGTERM drain writes another."""
    import time

    from distributed_llama_tpu.runtime.server import InferenceServer

    frdir = str(tmp_path / "fr")
    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=1, steps=4, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, watchdog_s=0.01,
                          flightrec_dir=frdir)
    srv.start()
    try:
        # a hung "dispatch": arm the watchdog well past its deadline
        with srv._watchdog:
            time.sleep(0.1)
        deadline = time.time() + 5
        while time.time() < deadline and not os.listdir(frdir):
            time.sleep(0.01)
        bundles = [os.path.join(frdir, f) for f in os.listdir(frdir)]
        assert bundles, "watchdog trip produced no bundle"
        b = load_bundle(bundles[0])
        assert b["reason"] == "watchdog"
        assert any(e["event"] == "watchdog" for e in b["events"])
        assert any(e["event"] == "server.start" for e in b["events"])
    finally:
        srv.stop()
    # the drain trigger, on a fresh server (start/drain lifecycle)
    srv2 = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                           slots=1, steps=4, temperature=0.0, topp=0.9,
                           seed=5, quiet=True, flightrec_dir=frdir)
    srv2.start()
    n_before = len(os.listdir(frdir))
    srv2.drain(budget_s=0.5)
    dumps = [os.path.join(frdir, f) for f in os.listdir(frdir)
             if "sigterm_drain" in f]
    assert len(os.listdir(frdir)) == n_before + 1 and dumps
    assert load_bundle(dumps[0])["reason"] == "sigterm_drain"


def test_supervisor_crash_loop_dumps_bundle(tmp_path):
    """The crash-loop trigger: supervise() drops a bundle before each
    respawn of a crashing child."""
    from distributed_llama_tpu.runtime.supervisor import supervise

    frdir = str(tmp_path / "fr")
    rcs = iter([3, 0])

    class _Proc:
        def __init__(self):
            self.pid = 4242
            self._rc = next(rcs)

        def wait(self):
            return self._rc

        def poll(self):
            return self._rc

    rc = supervise(["child"], popen=lambda cmd: _Proc(),
                   sleep=lambda s: None, install_signals=False,
                   flightrec_dir=frdir)
    assert rc == 0
    bundles = [f for f in os.listdir(frdir) if "crash_loop" in f]
    assert len(bundles) == 1
    b = load_bundle(os.path.join(frdir, bundles[0]))
    events = [e["event"] for e in b["events"]]
    assert "supervisor.spawn" in events and "supervisor.crash" in events
    crash = [e for e in b["events"]
             if e["event"] == "supervisor.crash"][0]
    assert crash["rc"] == 3


def test_is_bundle_file_sniffs(tmp_path):
    not_bundle = tmp_path / "x.json"
    not_bundle.write_text('{"kind": "dllama-trace"}')
    assert not is_bundle_file(str(not_bundle))
    assert not is_bundle_file(str(tmp_path / "missing.json"))
    garbage = tmp_path / "g.json"
    garbage.write_text("{{{")
    assert not is_bundle_file(str(garbage))


def test_bundle_scheduler_forensics_sections(tmp_path):
    """ISSUE 16: a recorder bound to the census ring and ledger book
    puts the dispatch tail + the OPEN bills into the bundle; bundles
    without the sections (older builds) stay loadable, and damaged
    sections are rejected by name."""
    from distributed_llama_tpu.obs.ledger import CensusRing, LedgerBook

    ring = CensusRing(slots=4)
    ring.record("decode", steps=2, active=3, parked={"pool_dry": 1},
                queue_depth=1, pages_held=9)
    book = LedgerBook()
    book.open_request(5, "interactive").charge_tokens(3)
    fr = FlightRecorder()
    fr.bind(census=ring, ledgers=book)
    bundle = fr.snapshot_bundle("watchdog")
    validate_bundle(bundle)
    assert bundle["census_tail"][0]["kind"] == "decode"
    assert bundle["open_ledgers"][0]["tokens"] == 3

    path = tmp_path / "b.json"
    path.write_text(json.dumps(bundle))
    assert load_bundle(str(path))["open_ledgers"][0]["rid"] == 5

    legacy = dict(bundle)
    del legacy["census_tail"], legacy["open_ledgers"]
    validate_bundle(legacy)  # validate-if-present: old bundles load

    broken = dict(bundle, census_tail=["not-a-record"])
    with pytest.raises(ValueError, match="census_tail"):
        validate_bundle(broken)
    broken = dict(bundle, open_ledgers={"rid": 5})
    with pytest.raises(ValueError, match="open_ledgers"):
        validate_bundle(broken)


def test_bundle_incident_kind_stamp(tmp_path):
    """ISSUE 20: an incident-triggered dump stamps the detector kind in
    the bundle header; other triggers omit it; bundles from pre-
    watchtower builds (no key) stay loadable; a malformed stamp is
    rejected by name."""
    from distributed_llama_tpu.obs.flightrec import REASON_INCIDENT

    fr = FlightRecorder()
    fr.note("watch.incident", kind="page_leak")
    path = fr.dump(str(tmp_path), REASON_INCIDENT,
                   incident_kind="page_leak")
    bundle = load_bundle(path)
    assert bundle["reason"] == "incident"
    assert bundle["incident_kind"] == "page_leak"
    # non-incident triggers carry NO stamp (absent, not null)
    plain = fr.snapshot_bundle("watchdog")
    assert "incident_kind" not in plain
    validate_bundle(plain)
    for bad in ("", 7):
        with pytest.raises(ValueError, match="incident_kind"):
            validate_bundle(dict(bundle, incident_kind=bad))
    # tracecheck surfaces the stamp in its summary line
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "tools",
            "tracecheck.py"), path],
        capture_output=True, text=True)
    assert proc.returncode == 0
    assert "incident_kind=page_leak" in proc.stdout
