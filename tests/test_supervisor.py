"""Serving supervision (runtime/supervisor.py, ISSUE 9): health state
machine legality, step-watchdog arm/trip/disarm semantics, crash-loop
backoff, and the serve --supervise respawn wrapper (faked Popen)."""

import threading
import time

import pytest

from distributed_llama_tpu.runtime.supervisor import (HEALTH_CODES,
                                                      CrashLoopBackoff,
                                                      HealthMonitor,
                                                      StepWatchdog,
                                                      serve_child_cmd,
                                                      supervise)

# ------------------------------------------------------------- health


def test_health_normal_lifecycle_and_gauge():
    from distributed_llama_tpu.obs.metrics import Registry

    reg = Registry()
    h = HealthMonitor(reg)
    gauge = reg.get("dllama_health_state")
    assert h.state == "starting" and gauge.value == 0
    assert h.to("serving") is True
    assert h.to("serving") is False  # same-state: no-op
    assert h.to("degraded") and h.to("serving")
    assert h.to("draining") and gauge.value == HEALTH_CODES["draining"]
    assert h.to("stopped") and gauge.value == HEALTH_CODES["stopped"]


def test_health_illegal_transitions_raise():
    h = HealthMonitor()
    h.to("serving")
    h.to("draining")
    with pytest.raises(ValueError):
        h.to("serving")  # draining only moves to stopped
    with pytest.raises(ValueError):
        # a watchdog trip mid-drain must not bounce the server through
        # degraded (whence -> serving would reopen admission mid-drain)
        h.to("degraded")
    h.to("stopped")
    for state in ("serving", "draining", "degraded", "starting"):
        with pytest.raises(ValueError):
            h.to(state)  # a stopped server never comes back
    with pytest.raises(ValueError):
        HealthMonitor().to("zombie")


def test_health_fault_states_enterable_from_any_live_state():
    """Fault paths must never crash on bookkeeping: degraded and stopped
    are reachable from every live state."""
    h = HealthMonitor()
    assert h.to("degraded")  # even from starting
    assert h.to("stopped")


# ----------------------------------------------------------- watchdog


def test_watchdog_trips_once_per_overrun_and_recovers():
    trips = []
    wd = StepWatchdog(0.03, on_hang=trips.append)
    try:
        with wd:  # fast dispatch: no trip
            pass
        time.sleep(0.08)
        assert wd.trips == 0 and not trips
        with wd:  # hung dispatch: exactly one trip, however long it runs
            time.sleep(0.1)
            assert wd.overdue
        assert wd.trips == 1 and len(trips) == 1
        assert trips[0] >= 0.03
        assert not wd.overdue  # disarmed
        with wd:
            time.sleep(0.1)
        assert wd.trips == 2  # re-arming re-enables the deadline
    finally:
        wd.close()


def test_watchdog_requires_positive_timeout():
    with pytest.raises(ValueError):
        StepWatchdog(0.0)


def test_watchdog_broken_callback_does_not_kill_monitor():
    def boom(elapsed):
        raise RuntimeError("broken callback")

    wd = StepWatchdog(0.02, on_hang=boom)
    try:
        with wd:
            time.sleep(0.06)
        with wd:
            time.sleep(0.06)
        assert wd.trips == 2  # monitor survived the first raise
    finally:
        wd.close()


def test_watchdog_close_joins_monitor():
    wd = StepWatchdog(10.0)
    wd.close()
    assert not wd._thread.is_alive()


# ------------------------------------------------------------ backoff


def test_crash_loop_backoff_doubles_and_resets():
    b = CrashLoopBackoff(initial_s=1.0, max_s=8.0, healthy_s=30.0)
    assert b.next_delay(0.1) == 1.0
    assert b.next_delay(0.1) == 2.0
    assert b.next_delay(0.1) == 4.0
    assert b.next_delay(0.1) == 8.0
    assert b.next_delay(0.1) == 8.0  # capped
    assert b.next_delay(31.0) == 1.0  # healthy child resets the loop
    assert b.next_delay(0.1) == 2.0


# ---------------------------------------------------------- supervise


class _FakeProc:
    def __init__(self, rc):
        self.rc = rc
        self.pid = 4242
        self.signals = []

    def wait(self):
        return self.rc

    def poll(self):
        return self.rc

    def send_signal(self, sig):
        self.signals.append(sig)


def test_supervise_restarts_until_clean_exit():
    rcs = iter([1, 1, 0])
    spawned = []

    def popen(cmd):
        p = _FakeProc(next(rcs))
        spawned.append(p)
        return p

    sleeps = []
    rc = supervise(["child"], backoff=CrashLoopBackoff(initial_s=0.01),
                   sleep=sleeps.append, popen=popen,
                   install_signals=False)
    assert rc == 0 and len(spawned) == 3
    assert sleeps == [0.01, 0.02]


def test_supervise_respects_restart_budget():
    def popen(cmd):
        return _FakeProc(3)

    rc = supervise(["child"], max_restarts=2,
                   backoff=CrashLoopBackoff(initial_s=0.0),
                   sleep=lambda s: None, popen=popen,
                   install_signals=False)
    assert rc == 3  # gave up with the child's exit code


def test_supervise_sigterm_forwards_and_does_not_respawn():
    """SIGTERM forwards to the child exactly once; when the child then
    exits non-zero (drain raced the kill), the supervisor still treats it
    as termination, not a crash loop."""
    import signal as _signal

    procs = []

    class _SlowProc(_FakeProc):
        def __init__(self):
            super().__init__(1)
            self._rc = None

        def wait(self):
            while self._rc is None:
                time.sleep(0.005)
            return self._rc

        def poll(self):
            return self._rc

        def send_signal(self, sig):
            self.signals.append(sig)
            self._rc = 1  # dies to the forwarded signal

    def popen(cmd):
        p = _SlowProc()
        procs.append(p)
        return p

    # supervise installs its handler on the MAIN thread (this one); a
    # helper delivers the handler directly once the child is up —
    # simulating the signal without kill()
    def trigger():
        while not procs:
            time.sleep(0.005)
        _signal.getsignal(_signal.SIGTERM)(_signal.SIGTERM, None)

    helper = threading.Thread(target=trigger)
    helper.start()
    prev = _signal.getsignal(_signal.SIGTERM)
    try:
        rc = supervise(["child"], popen=popen, install_signals=True,
                       sleep=lambda s: None)
    finally:
        helper.join(timeout=10)
        _signal.signal(_signal.SIGTERM, prev)
    assert rc == 1 and len(procs) == 1  # no respawn after SIGTERM
    assert procs[0].signals == [_signal.SIGTERM]


def test_serve_child_cmd_strips_supervision_flags():
    import sys

    argv = ["--model", "m.bin", "--supervise", "--max-restarts", "3",
            "--journal", "j.ndjson", "--max-restarts=5", "--port", "0"]
    cmd = serve_child_cmd(argv)
    assert cmd[:4] == [sys.executable, "-m", "distributed_llama_tpu",
                       "serve"]
    rest = cmd[4:]
    assert "--supervise" not in rest
    assert not any(a.startswith("--max-restarts") for a in rest)
    assert "3" not in rest  # the flag's VALUE went with it
    assert rest == ["--model", "m.bin", "--journal", "j.ndjson",
                    "--port", "0"]
