"""Root -> worker weight streaming (io/stream.py): the reference's
zero-local-files worker capability (transformer.cpp:250-273, 354-380),
rebuilt as a byte-range file service + fetch-then-normal-load.
"""

import os
import threading

import numpy as np
import pytest

from distributed_llama_tpu.io.stream import WeightServer, fetch_model


@pytest.fixture()
def served_file(tmp_path):
    src = tmp_path / "model.bin"
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 9_000_017, dtype=np.uint8).tobytes()
    src.write_bytes(data)
    server = WeightServer(str(src), host="127.0.0.1")
    yield server, str(src), data, tmp_path
    server.close()


def test_fetch_roundtrip_byte_exact(served_file):
    server, src, data, tmp_path = served_file
    dst = str(tmp_path / "fetched" / "model.bin")
    got = fetch_model(f"127.0.0.1:{server.port}", dst, quiet=True)
    assert got == dst
    assert open(dst, "rb").read() == data
    assert not os.path.exists(dst + ".part")  # atomic rename cleaned up


def test_fetch_skips_existing_cache(served_file):
    server, src, data, tmp_path = served_file
    dst = str(tmp_path / "cache.bin")
    with open(dst, "wb") as f:
        f.write(data)
    before = os.path.getmtime(dst)
    fetch_model(f"127.0.0.1:{server.port}", dst, quiet=True)
    assert os.path.getmtime(dst) == before  # untouched: size matched


def test_concurrent_fetchers(served_file):
    """Several workers fetch simultaneously (the reference serializes its
    scatter; the threaded server need not)."""
    server, src, data, tmp_path = served_file
    errs = []

    def fetch(i):
        try:
            p = str(tmp_path / f"w{i}" / "model.bin")
            fetch_model(f"127.0.0.1:{server.port}", p, quiet=True)
            assert open(p, "rb").read() == data
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=fetch, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs


def test_protocol_mismatch_raises(tmp_path):
    """A non-weight-server endpoint must fail loudly, not hang or corrupt."""
    import socketserver

    class Junk(socketserver.BaseRequestHandler):
        def handle(self):
            self.request.recv(64)
            self.request.sendall(b"HTTP/1.1 200 OK\r\n" + b"x" * 16)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    srv = Server(("127.0.0.1", 0), Junk)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with pytest.raises(ValueError, match="protocol mismatch"):
            fetch_model(f"127.0.0.1:{srv.server_address[1]}",
                        str(tmp_path / "x.bin"), quiet=True)
    finally:
        srv.shutdown()
        srv.server_close()


def test_fetch_repairs_truncated_cache(served_file):
    """A wrong-size local file must be re-fetched, not trusted (the CLI
    calls fetch_model unconditionally; staleness is decided HERE)."""
    server, src, data, tmp_path = served_file
    dst = str(tmp_path / "stale.bin")
    with open(dst, "wb") as f:
        f.write(data[:1000])  # truncated earlier copy
    fetch_model(f"127.0.0.1:{server.port}", dst, quiet=True)
    assert open(dst, "rb").read() == data


def test_connect_retry_tolerates_late_server(tmp_path):
    """Worker starting before the root's server binds must retry, not die
    (the reference's worker likewise blocks in accept())."""
    import socket as _socket
    import threading
    import time as _time

    src = tmp_path / "m.bin"
    src.write_bytes(b"z" * 4096)
    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    box = {}

    def late_start():
        _time.sleep(1.0)
        box["server"] = WeightServer(str(src), host="127.0.0.1", port=port)

    t = threading.Thread(target=late_start)
    t.start()
    try:
        dst = str(tmp_path / "out.bin")
        fetch_model(f"127.0.0.1:{port}", dst, quiet=True, connect_window=15)
        assert open(dst, "rb").read() == b"z" * 4096
    finally:
        t.join()
        box["server"].close()
