"""Root -> worker weight streaming (io/stream.py): the reference's
zero-local-files worker capability (transformer.cpp:250-273, 354-380),
rebuilt as a byte-range file service + fetch-then-normal-load.
"""

import os
import threading

import numpy as np
import pytest

from distributed_llama_tpu.io.stream import WeightServer, fetch_model


@pytest.fixture()
def served_file(tmp_path):
    src = tmp_path / "model.bin"
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, 9_000_017, dtype=np.uint8).tobytes()
    src.write_bytes(data)
    server = WeightServer(str(src), host="127.0.0.1")
    yield server, str(src), data, tmp_path
    server.close()


def test_fetch_roundtrip_byte_exact(served_file):
    server, src, data, tmp_path = served_file
    dst = str(tmp_path / "fetched" / "model.bin")
    got = fetch_model(f"127.0.0.1:{server.port}", dst, quiet=True)
    assert got == dst
    assert open(dst, "rb").read() == data
    assert not os.path.exists(dst + ".part")  # atomic rename cleaned up


def test_fetch_skips_existing_cache(served_file):
    server, src, data, tmp_path = served_file
    dst = str(tmp_path / "cache.bin")
    with open(dst, "wb") as f:
        f.write(data)
    before = os.path.getmtime(dst)
    fetch_model(f"127.0.0.1:{server.port}", dst, quiet=True)
    assert os.path.getmtime(dst) == before  # untouched: size matched


def test_concurrent_fetchers(served_file):
    """Several workers fetch simultaneously (the reference serializes its
    scatter; the threaded server need not)."""
    server, src, data, tmp_path = served_file
    errs = []

    def fetch(i):
        try:
            p = str(tmp_path / f"w{i}" / "model.bin")
            fetch_model(f"127.0.0.1:{server.port}", p, quiet=True)
            assert open(p, "rb").read() == data
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    threads = [threading.Thread(target=fetch, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs


def test_protocol_mismatch_raises(tmp_path):
    """A non-weight-server endpoint must fail loudly, not hang or corrupt."""
    import socketserver

    class Junk(socketserver.BaseRequestHandler):
        def handle(self):
            self.request.recv(64)
            self.request.sendall(b"HTTP/1.1 200 OK\r\n" + b"x" * 16)

    class Server(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

    srv = Server(("127.0.0.1", 0), Junk)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with pytest.raises(ValueError, match="protocol mismatch"):
            fetch_model(f"127.0.0.1:{srv.server_address[1]}",
                        str(tmp_path / "x.bin"), quiet=True)
    finally:
        srv.shutdown()
        srv.server_close()


def test_fetch_repairs_truncated_cache(served_file):
    """A wrong-size local file must be re-fetched, not trusted (the CLI
    calls fetch_model unconditionally; staleness is decided HERE)."""
    server, src, data, tmp_path = served_file
    dst = str(tmp_path / "stale.bin")
    with open(dst, "wb") as f:
        f.write(data[:1000])  # truncated earlier copy
    fetch_model(f"127.0.0.1:{server.port}", dst, quiet=True)
    assert open(dst, "rb").read() == data


def test_connect_retry_tolerates_late_server(tmp_path):
    """Worker starting before the root's server binds must retry, not die
    (the reference's worker likewise blocks in accept())."""
    import socket as _socket
    import threading
    import time as _time

    src = tmp_path / "m.bin"
    src.write_bytes(b"z" * 4096)
    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    box = {}

    def late_start():
        _time.sleep(1.0)
        box["server"] = WeightServer(str(src), host="127.0.0.1", port=port)

    t = threading.Thread(target=late_start)
    t.start()
    try:
        dst = str(tmp_path / "out.bin")
        fetch_model(f"127.0.0.1:{port}", dst, quiet=True, connect_window=15)
        assert open(dst, "rb").read() == b"z" * 4096
    finally:
        t.join()
        box["server"].close()


# ---------------------------------------------------------------------------
# Slice-granular streaming (VERDICT r2 #5): fetch only a host's tp bands
# ---------------------------------------------------------------------------

def _tiny_spec():
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.ops.quants import FloatType

    return TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=300, seq_len=32,
                           weights_float_type=FloatType.Q40)


def _write_tiny_model(path, spec, seed=5):
    from distributed_llama_tpu.io.loader import write_model

    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.1).astype(np.float32)

    tensors = {"tok_embedding": t(spec.vocab_size, spec.dim),
               "rms_att": 1 + t(spec.n_layers, spec.dim),
               "rms_ffn": 1 + t(spec.n_layers, spec.dim),
               "rms_final": 1 + t(spec.dim),
               "wcls": t(spec.vocab_size, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        tensors[name] = t(spec.n_layers, *shape)
    write_model(path, spec, tensors)
    return tensors


def test_range_algebra():
    from distributed_llama_tpu.io.stream import merge_ranges, subtract_ranges

    assert merge_ranges([(10, 5), (0, 4), (14, 6), (3, 2)]) == [
        (0, 5), (10, 10)]
    assert subtract_ranges([(0, 20)], [(5, 5)]) == [(0, 5), (10, 10)]
    assert subtract_ranges([(0, 10)], [(0, 10)]) == []
    assert subtract_ranges([(0, 10)], []) == [(0, 10)]
    assert subtract_ranges([(5, 10)], [(0, 7), (12, 100)]) == [(7, 5)]


def test_needed_ranges_tp2_half_matmul_bytes():
    """A tp=2 single-rank host fetches the header + replicated tensors in
    full and exactly HALF of every matmul tensor's bytes (VERDICT r2 #5's
    acceptance: ~half the file's matmul bytes; reference scatter
    transformer.cpp:250-273)."""
    from distributed_llama_tpu.io.loader import tensor_byte_ranges
    from distributed_llama_tpu.io.stream import needed_byte_ranges
    from distributed_llama_tpu.models.spec import HEADER_BYTES

    spec = _tiny_spec()
    trs = tensor_byte_ranges(spec)
    matmul = sum(tr.nbytes for tr in trs if tr.rows is not None)
    repl = sum(tr.nbytes for tr in trs
               if tr.rows is None and tr.name != "_rope_gap")
    need = needed_byte_ranges(spec, 2, {0})
    got = sum(ln for _, ln in need)
    assert got == HEADER_BYTES + repl + matmul // 2
    # both ranks = the whole file minus the rope gap
    both = sum(ln for _, ln in needed_byte_ranges(spec, 2, {0, 1}))
    assert both == HEADER_BYTES + repl + matmul


def test_fetch_model_slices_e2e(tmp_path):
    """Slice fetch -> sparse file: fetched bands byte-identical, unfetched
    bands zero, sidecar enables the top-up path, and topping up to all
    ranks reproduces the full file (modulo the rope gap, zeros both ways)."""
    from distributed_llama_tpu.io.loader import (load_model,
                                                 tensor_byte_ranges)
    from distributed_llama_tpu.io.stream import fetch_model_slices
    from distributed_llama_tpu.ops.quants import FloatType

    spec = _tiny_spec()
    src = str(tmp_path / "model.bin")
    _write_tiny_model(src, spec)
    server = WeightServer(src, host="127.0.0.1")
    try:
        dst = str(tmp_path / "worker" / "model.bin")
        addr = f"127.0.0.1:{server.port}"
        fetch_model_slices(addr, dst, FloatType.Q40, 2, {1}, quiet=True)
        assert os.path.getsize(dst) == os.path.getsize(src)
        assert os.path.exists(dst + ".slices")

        _, want = load_model(src, weights_float_type=FloatType.Q40)
        _, got = load_model(dst, weights_float_type=FloatType.Q40)
        for name in ("tok_embedding", "rms_att", "rms_ffn", "rms_final"):
            np.testing.assert_array_equal(got[name], want[name])
        for tr in tensor_byte_ranges(spec):
            if tr.rows is None or tr.layer not in (None, 0):
                continue
            w, g = want[tr.name], got[tr.name]
            if tr.layer == 0:
                w = type(w)(*(a[0] for a in w)) if hasattr(w, "qs") else w[0]
                g = type(g)(*(a[0] for a in g)) if hasattr(g, "qs") else g[0]
            half = tr.rows // 2
            wq, gq = (w.qs, g.qs) if hasattr(w, "qs") else (w, g)
            wd, gd = (w.d16, g.d16) if hasattr(w, "qs") else (None, None)
            np.testing.assert_array_equal(gq[half:], wq[half:])  # rank 1
            assert not gq[:half].any()                           # rank 0 hole
            if wd is not None:
                np.testing.assert_array_equal(gd[half:], wd[half:])
                assert not gd[:half].any()

        # cache hit: same ranks fetch nothing (mtime untouched)
        before = os.path.getmtime(dst)
        fetch_model_slices(addr, dst, FloatType.Q40, 2, {1}, quiet=True)
        assert os.path.getmtime(dst) == before
        # top-up: adding rank 0 completes the file byte-for-byte
        fetch_model_slices(addr, dst, FloatType.Q40, 2, {0, 1}, quiet=True)
        assert open(dst, "rb").read() == open(src, "rb").read()
    finally:
        server.close()


def _served_slices(tmp_path):
    """(server, addr, src, dst) for a tiny served model + a worker dst."""
    spec = _tiny_spec()
    src = str(tmp_path / "model.bin")
    _write_tiny_model(src, spec)
    server = WeightServer(src, host="127.0.0.1")
    return server, f"127.0.0.1:{server.port}", src, str(
        tmp_path / "w" / "model.bin")


def test_corrupt_sidecar_triggers_full_refetch(tmp_path):
    """A sidecar that no longer parses vouches for NOTHING: the fetch must
    ignore it, re-fetch every needed range, and leave a repaired sidecar
    (ISSUE 9 satellite — sidecar edge cases)."""
    import json

    from distributed_llama_tpu.io.stream import fetch_model_slices
    from distributed_llama_tpu.ops.quants import FloatType

    server, addr, src, dst = _served_slices(tmp_path)
    try:
        fetch_model_slices(addr, dst, FloatType.Q40, 1, {0}, quiet=True)
        good = open(dst, "rb").read()
        # corrupt the sidecar AND zero the data: only a real re-fetch can
        # restore the bytes (a trusted-sidecar skip would keep the zeros)
        with open(dst + ".slices", "w") as fh:
            fh.write('{"size": 12, "ran')  # torn/garbage JSON
        with open(dst, "r+b") as fh:
            fh.write(b"\0" * 4096)
        fetch_model_slices(addr, dst, FloatType.Q40, 1, {0}, quiet=True)
        assert open(dst, "rb").read() == good
        with open(dst + ".slices") as fh:
            assert json.load(fh)["ranges"]  # repaired, real ranges again
    finally:
        server.close()


def test_wrong_size_sidecar_ignored(tmp_path):
    """A sidecar whose recorded size disagrees with the served file
    describes a DIFFERENT model: nothing in it is usable — the fetch
    starts from zero ranges instead of trusting stale offsets."""
    import json

    from distributed_llama_tpu.io.stream import fetch_model_slices
    from distributed_llama_tpu.ops.quants import FloatType

    server, addr, src, dst = _served_slices(tmp_path)
    try:
        fetch_model_slices(addr, dst, FloatType.Q40, 1, {0}, quiet=True)
        good = open(dst, "rb").read()
        size = os.path.getsize(src)
        with open(dst, "r+b") as fh:  # damage the data the stale sidecar
            fh.write(b"\0" * 4096)    # would have vouched for
        with open(dst + ".slices", "w") as fh:
            json.dump({"size": size + 1, "ranges": [[0, size]]}, fh)
        fetch_model_slices(addr, dst, FloatType.Q40, 1, {0}, quiet=True)
        assert open(dst, "rb").read() == good
    finally:
        server.close()


def test_killed_fetch_residue_refetched_not_trusted(tmp_path):
    """Killed-fetch residue — data written to full size but the sidecar
    GONE — must re-fetch: a right-sized file without a sidecar is only a
    cache hit when its header matches the served bytes (holes read as
    zeros and fail that check)."""
    from distributed_llama_tpu.io.stream import fetch_model_slices
    from distributed_llama_tpu.ops.quants import FloatType

    server, addr, src, dst = _served_slices(tmp_path)
    try:
        # full-size file of zeros, no sidecar: the pre-ISSUE-9 code took
        # this as a complete whole-file cache and served zeros as weights
        os.makedirs(os.path.dirname(dst))
        with open(dst, "wb") as fh:
            fh.truncate(os.path.getsize(src))
        fetch_model_slices(addr, dst, FloatType.Q40, 1, {0}, quiet=True)
        ref = str(tmp_path / "ref" / "model.bin")
        fetch_model_slices(addr, ref, FloatType.Q40, 1, {0}, quiet=True)
        assert open(dst, "rb").read() == open(ref, "rb").read()
    finally:
        server.close()


def test_crc_mismatch_refetches_damaged_range(tmp_path):
    """Sidecar CRCs vouch for on-disk bytes: flip one resident byte and
    the next fetch must fail that range's CRC and repair exactly it."""
    from distributed_llama_tpu.io.stream import fetch_model_slices
    from distributed_llama_tpu.ops.quants import FloatType

    server, addr, src, dst = _served_slices(tmp_path)
    try:
        fetch_model_slices(addr, dst, FloatType.Q40, 1, {0}, quiet=True)
        good = open(dst, "rb").read()
        pos = os.path.getsize(src) // 2
        with open(dst, "r+b") as fh:
            fh.seek(pos)
            byte = fh.read(1)
            fh.seek(pos)
            fh.write(bytes([byte[0] ^ 0xFF]))
        before = os.path.getmtime(dst)
        fetch_model_slices(addr, dst, FloatType.Q40, 1, {0}, quiet=True)
        assert open(dst, "rb").read() == good
        assert os.path.getmtime(dst) != before  # it actually re-fetched
    finally:
        server.close()


def test_connect_nontransient_raises_immediately(monkeypatch):
    """A non-transient connect failure (bad address, permission) must
    raise on the FIRST attempt instead of burning the connect window —
    only transient errno values retry (ISSUE 9 satellite)."""
    import errno
    import time as _time

    from distributed_llama_tpu.io import stream as stream_mod
    from distributed_llama_tpu.io.stream import _connect_with_retry

    attempts = {"n": 0}

    def denied(addr, timeout=None):
        attempts["n"] += 1
        raise OSError(errno.EACCES, "permission denied")

    monkeypatch.setattr(stream_mod.socket, "create_connection", denied)
    slept: list[float] = []
    monkeypatch.setattr(_time, "sleep", lambda d: slept.append(d))
    with pytest.raises(OSError):
        _connect_with_retry("127.0.0.1", 1, timeout=1, connect_window=30)
    assert attempts["n"] == 1 and not slept


def test_connect_backoff_grows_exponentially(monkeypatch):
    """Transient refusals back off exponentially (50 ms doubling), not a
    fixed 0.25 s spin."""
    import socket as _socket
    import time as _time

    from distributed_llama_tpu.io.stream import (_connect_with_retry,
                                                 _is_transient)

    assert _is_transient(ConnectionRefusedError())
    assert _is_transient(_socket.timeout())
    assert not _is_transient(_socket.gaierror())
    # resolver-not-ready (container boot race) IS transient; a bad name
    # is not
    assert _is_transient(_socket.gaierror(_socket.EAI_AGAIN, "try again"))
    assert not _is_transient(_socket.gaierror(_socket.EAI_NONAME, "nope"))
    assert not _is_transient(OSError(28, "No space left on device"))

    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    # nothing listens on ``port`` now: every connect is refused (transient)
    delays: list[float] = []
    monkeypatch.setattr(_time, "sleep", lambda d: delays.append(d))
    with pytest.raises(OSError):
        _connect_with_retry("127.0.0.1", port, timeout=1,
                            connect_window=0.3)
    assert delays[:3] == [0.05, 0.1, 0.2]


def test_sparse_file_never_mistaken_for_full(tmp_path):
    """Crash-safety of the slice cache protocol (review findings): (1) a
    fetch killed before any range lands must leave a sidecar claiming ZERO
    ranges — never a right-sized holey file that reads as a full cache;
    (2) fetch_model must refuse a sparse file as a whole-file cache hit and
    repair it (deleting the sidecar)."""
    from distributed_llama_tpu.io.stream import fetch_model_slices
    from distributed_llama_tpu.ops.quants import FloatType

    spec = _tiny_spec()
    src = str(tmp_path / "model.bin")
    _write_tiny_model(src, spec)
    server = WeightServer(src, host="127.0.0.1")
    try:
        addr = f"127.0.0.1:{server.port}"
        dst = str(tmp_path / "w" / "model.bin")

        # simulate the killed-fresh-fetch residue: full-size zero file +
        # the empty sidecar the fetch writes BEFORE its first byte
        os.makedirs(os.path.dirname(dst))
        with open(dst, "wb") as fh:
            fh.truncate(os.path.getsize(src))
        import json

        with open(dst + ".slices", "w") as fh:
            json.dump({"size": os.path.getsize(src), "ranges": []}, fh)

        # slice fetch does NOT trust the holes: it re-fetches its ranges
        fetch_model_slices(addr, dst, FloatType.Q40, 2, {0}, quiet=True)
        with open(dst + ".slices") as fh:
            assert json.load(fh)["ranges"]  # real ranges recorded now

        # whole-file fetch refuses the sparse file as a hit: repairs to a
        # byte-identical full file and drops the sidecar
        fetch_model(addr, dst, quiet=True)
        assert open(dst, "rb").read() == open(src, "rb").read()
        assert not os.path.exists(dst + ".slices")
    finally:
        server.close()
