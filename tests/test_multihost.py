"""Multi-host (DCN) smoke test: two REAL processes joined via
jax.distributed over localhost, using the reference's root/worker CLI
vocabulary (inference --host-id 0 / worker --host-id 1), must generate the
same token stream as a single-process run.

This covers what the reference only ever validated manually on 8 Raspberry
Pis (SURVEY.md §4: 'Multi-node testing: manual only') — here the multi-host
path is a CI test: each process contributes one virtual CPU device, the
global mesh is tp=2 across processes, and the collectives ride the
jax.distributed transport.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from distributed_llama_tpu.io.loader import write_model
from distributed_llama_tpu.io.tokenizer import write_tokenizer
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

# GQA (kv < heads): the 2-process DCN test must keep grouped-query coverage
SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=300, seq_len=32,
                       weights_float_type=FloatType.Q40)
# MHA spec whose kv heads shard 4 ways, for the tp=4 two-hosts test
SPEC4 = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                        n_kv_heads=4, vocab_size=300, seq_len=32,
                        weights_float_type=FloatType.Q40)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_model_files(d, spec=SPEC):
    rng = np.random.default_rng(5)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.1).astype(np.float32)

    tensors = {"tok_embedding": t(spec.vocab_size, spec.dim),
               "rms_att": 1 + t(spec.n_layers, spec.dim),
               "rms_ffn": 1 + t(spec.n_layers, spec.dim),
               "rms_final": 1 + t(spec.dim),
               "wcls": t(spec.vocab_size, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        tensors[name] = t(spec.n_layers, *shape)
    model = str(d / "model.bin")
    write_model(model, spec, tensors)
    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    while len(pieces) < spec.vocab_size:
        pieces.append(f"tok{len(pieces)}".encode())
    tok = str(d / "tok.bin")
    write_tokenizer(tok, pieces, [0.0] * len(pieces))
    return model, tok


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run(mode, model, tok, host_id, coordinator, n_devices, cwd, tp=2,
         extra=()):
    """2-host spawn with the classic generation args (delegates to _run_n —
    one place owns the spawn environment)."""
    gen = ("--prompt", "hi", "--steps", "6", "--temperature", "0.9",
           "--topp", "0.9")
    return _run_n(mode, model, tok, host_id, coordinator, 2, n_devices, cwd,
                  tp=tp, extra=gen + tuple(extra))


def _pieces(out):
    return [ln.rsplit("'", 2)[-2] for ln in out.splitlines()
            if ln.startswith("🔶")]


def _rows(out, drop_done=False):
    """Batch/continuous output rows ("[N] '...'") — Gloo connection logs
    also start with "[", and continuous mode interleaves "[N] done:" lines."""
    import re as _re

    return [ln for ln in out.splitlines()
            if _re.match(r"^\[\d+\] ", ln)
            and not (drop_done and "] done:" in ln)]



def test_two_process_inference_matches_single(tmp_path):
    model, tok = _write_model_files(tmp_path)

    # single process, 2 local virtual devices, tp=2
    cwd = str(tmp_path)
    p = _run("inference", model, tok, None, None, 2, cwd)
    out_single, err = p.communicate(timeout=300)
    assert p.returncode == 0, err[-2000:]
    want = _pieces(out_single)
    assert want, out_single

    # two processes, 1 device each, same global tp=2 mesh over DCN
    port = _free_port()
    coord = f"127.0.0.1:{port}"
    root = _run("inference", model, tok, 0, coord, 1, cwd)
    worker = _run("worker", model, tok, 1, coord, 1, cwd)
    out_root, err_root = root.communicate(timeout=360)
    out_worker, err_worker = worker.communicate(timeout=60)
    assert root.returncode == 0, f"root: {err_root[-2000:]}"
    assert worker.returncode == 0, f"worker: {err_worker[-2000:]}"
    assert _pieces(out_root) == want, out_root
    assert _pieces(out_worker) == []  # workers run silent


def test_two_hosts_two_devices_each(tmp_path):
    """2 hosts x 2 local devices = a tp=4 global mesh where collectives
    cross BOTH the intra-process boundary (the ICI analog) and the process
    boundary (DCN) — the topology shape of a real multi-host pod slice."""
    model, tok = _write_model_files(tmp_path, SPEC4)
    cwd = str(tmp_path)

    p = _run("inference", model, tok, None, None, 4, cwd, tp=4)
    out_single, err = p.communicate(timeout=300)
    assert p.returncode == 0, err[-2000:]
    want = _pieces(out_single)
    assert want

    coord = f"127.0.0.1:{_free_port()}"
    root = _run("inference", model, tok, 0, coord, 2, cwd, tp=4)
    worker = _run("worker", model, tok, 1, coord, 2, cwd, tp=4)
    out_root, err_root = root.communicate(timeout=360)
    out_worker, err_worker = worker.communicate(timeout=60)
    assert root.returncode == 0, f"root: {err_root[-2000:]}"
    assert worker.returncode == 0, f"worker: {err_worker[-2000:]}"
    assert _pieces(out_root) == want, out_root


def test_two_process_batch_prompts_file(tmp_path):
    """The lockstep batch path (--prompts-file --tp) across two real
    processes: the sharded batch step's collectives ride DCN, every host
    runs the same fused loop, and the root's rows equal the single-process
    rows."""
    model, tok = _write_model_files(tmp_path)
    pf = str(tmp_path / "prompts.txt")
    with open(pf, "w") as fh:
        fh.write("hi\nhi hi\n")
    cwd = str(tmp_path)
    extra = ("--prompts-file", pf)

    p = _run("inference", model, tok, None, None, 2, cwd, extra=extra)
    out_single, err = p.communicate(timeout=300)
    assert p.returncode == 0, err[-2000:]
    want = _rows(out_single)
    assert len(want) == 2, out_single

    coord = f"127.0.0.1:{_free_port()}"
    root = _run("inference", model, tok, 0, coord, 1, cwd, extra=extra)
    worker = _run("worker", model, tok, 1, coord, 1, cwd, extra=extra)
    out_root, err_root = root.communicate(timeout=360)
    out_worker, err_worker = worker.communicate(timeout=60)
    assert root.returncode == 0, f"root: {err_root[-2000:]}"
    assert worker.returncode == 0, f"worker: {err_worker[-2000:]}"
    assert _rows(out_root) == want, out_root


def test_two_process_continuous(tmp_path):
    """Continuous batching across two real processes: both hosts run the
    SAME deterministic scheduler (admission order, per-request samplers),
    so every step's sharded collectives line up — the root's rows equal
    the single-process rows."""
    model, tok = _write_model_files(tmp_path)
    pf = str(tmp_path / "prompts.txt")
    with open(pf, "w") as fh:
        fh.write("hi\nhi hi\nhi\n")
    cwd = str(tmp_path)
    extra = ("--prompts-file", pf, "--continuous", "--slots", "2",
             "--prefill-chunk", "0")

    p = _run("inference", model, tok, None, None, 2, cwd, extra=extra)
    out_single, err = p.communicate(timeout=300)
    assert p.returncode == 0, err[-2000:]
    want = _rows(out_single, drop_done=True)
    assert len(want) == 3, out_single

    coord = f"127.0.0.1:{_free_port()}"
    root = _run("inference", model, tok, 0, coord, 1, cwd, extra=extra)
    worker = _run("worker", model, tok, 1, coord, 1, cwd, extra=extra)
    out_root, err_root = root.communicate(timeout=360)
    out_worker, err_worker = worker.communicate(timeout=60)
    assert root.returncode == 0, f"root: {err_root[-2000:]}"
    assert worker.returncode == 0, f"worker: {err_worker[-2000:]}"
    assert _rows(out_root, drop_done=True) == want, out_root


def _run_n(mode, model, tok, host_id, coordinator, n_hosts, n_devices, cwd,
           tp=4, extra=()):
    """Spawn one CLI process of an n-host run (THE spawn helper; _run wraps
    it for the 2-host generation tests). cwd is OUTSIDE the repo: some
    environments activate a hardware-backend shim keyed on the repo
    directory that overrides JAX_PLATFORMS=cpu."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    env["PYTHONPATH"] = REPO
    env.pop("DLLAMA_Q40_KERNEL", None)
    args = [sys.executable, "-m", "distributed_llama_tpu.frontend.cli", mode,
            "--model", model, "--tokenizer", tok,
            "--seed", "11", "--tp", str(tp), *extra]
    if coordinator:
        args += ["--coordinator", coordinator, "--num-hosts", str(n_hosts),
                 "--host-id", str(host_id)]
    return subprocess.Popen(args, cwd=cwd, env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)


def test_four_process_tp4_matches_single(tmp_path):
    """tp=4 with every slice on its OWN process (4 x 1 device) — the
    widest all-DCN topology this suite spawns (VERDICT r1 #8; the reference
    validated 8 socket nodes only by hand, README.md:40-50). Token stream
    must equal the single-process tp=4 run."""
    model, tok = _write_model_files(tmp_path, SPEC4)
    cwd = str(tmp_path)
    gen = ("--prompt", "hi", "--steps", "5", "--temperature", "0.9",
           "--topp", "0.9")

    p = _run_n("inference", model, tok, None, None, 1, 4, cwd, extra=gen)
    out_single, err = p.communicate(timeout=300)
    assert p.returncode == 0, err[-2000:]
    want = _pieces(out_single)
    assert want

    coord = f"127.0.0.1:{_free_port()}"
    procs = [_run_n("inference" if i == 0 else "worker", model, tok, i,
                    coord, 4, 1, cwd, extra=gen) for i in range(4)]
    outs = [p.communicate(timeout=420) for p in procs]
    for i, (p, (o, e)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {i}: {e[-2000:]}"
    assert _pieces(outs[0][0]) == want, outs[0][0]
    for o, _ in outs[1:]:
        assert _pieces(o) == []  # workers run silent


def test_two_process_train_dp_across_hosts(tmp_path):
    """Training with the dp axis CROSSING the host boundary: 2 processes x
    1 device = a global dp=2 mesh; every host feeds the identical global
    windows (the data schedule is a pure function of seed/step) and jit
    shards rows across hosts. Root's per-step losses must equal the
    single-process dp=2 run's."""
    model, tok = _write_model_files(tmp_path, SPEC4)
    data = str(tmp_path / "corpus.txt")
    with open(data, "w") as fh:
        fh.write("the quick brown fox jumps over the lazy dog " * 30)
    cwd = str(tmp_path)
    tr = ("--data", data, "--steps", "3", "--batch", "4", "--seq", "16",
          "--weights-float-type", "q40")

    def losses(out):
        return [ln.split("loss")[1].split()[0] for ln in out.splitlines()
                if ln.startswith("🔶 step")]

    p = _run_n("train", model, tok, None, None, 1, 2, cwd, tp=1,
               extra=tr + ("--dp", "2"))
    out_single, err = p.communicate(timeout=300)
    assert p.returncode == 0, err[-2000:]
    want = losses(out_single)
    assert len(want) == 3, out_single

    coord = f"127.0.0.1:{_free_port()}"
    procs = [_run_n("train", model, tok, i, coord, 2, 1, cwd, tp=1,
                    extra=tr + ("--dp", "2")) for i in range(2)]
    outs = [p.communicate(timeout=420) for p in procs]
    for i, (p, (o, e)) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {i}: {e[-2000:]}"
    assert losses(outs[0][0]) == want, outs[0][0]
    assert losses(outs[1][0]) == []  # non-root hosts run silent


def test_worker_streams_weights_from_root(tmp_path):
    """The reference's zero-local-files worker: the worker host starts with
    NO model file and fetches it from the root's --serve-weights endpoint
    (io/stream.py) before joining the mesh; output must equal the
    single-process run."""
    model, tok = _write_model_files(tmp_path)
    cwd = str(tmp_path)
    gen = ("--prompt", "hi", "--steps", "5", "--temperature", "0.9",
           "--topp", "0.9")

    p = _run_n("inference", model, tok, None, None, 1, 2, cwd, tp=2,
               extra=gen)
    out_single, err = p.communicate(timeout=300)
    assert p.returncode == 0, err[-2000:]
    want = _pieces(out_single)
    assert want

    wport = _free_port()
    coord = f"127.0.0.1:{_free_port()}"
    # worker's --model points into an EMPTY directory; only the fetch can
    # make the file exist
    wdir = tmp_path / "workerhost"
    wdir.mkdir()
    wmodel = str(wdir / "model.bin")
    root = _run_n("inference", model, tok, 0, coord, 2, 1, cwd, tp=2,
                  extra=gen + ("--serve-weights", str(wport)))
    # no sleep: fetch_model retries connection-refused while the root's
    # server binds (io/stream._connect_with_retry)
    worker = _run_n("worker", wmodel, tok, 1, coord, 2, 1, cwd, tp=2,
                    extra=gen + ("--model-from-root", f"127.0.0.1:{wport}"))
    out_root, err_root = root.communicate(timeout=420)
    out_worker, err_worker = worker.communicate(timeout=60)
    assert root.returncode == 0, f"root: {err_root[-2000:]}"
    assert worker.returncode == 0, f"worker: {err_worker[-2000:]}"
    assert _pieces(out_root) == want, out_root
    import os as _os

    assert _os.path.getsize(wmodel) == _os.path.getsize(model)  # fetched


def test_worker_streams_weight_slices_from_root(tmp_path):
    """--stream-slices: the worker host fetches ONLY its tp band of every
    matmul tensor (the reference's slice-granular scatter,
    transformer.cpp:250-273) — the sparse file passes the loader, the mesh
    cross-check accepts the assumed ranks, and output equals the
    single-process run. The sidecar must show materially less than the
    whole file fetched."""
    model, tok = _write_model_files(tmp_path)
    cwd = str(tmp_path)
    gen = ("--prompt", "hi", "--steps", "5", "--temperature", "0.9",
           "--topp", "0.9")

    p = _run_n("inference", model, tok, None, None, 1, 2, cwd, tp=2,
               extra=gen)
    out_single, err = p.communicate(timeout=300)
    assert p.returncode == 0, err[-2000:]
    want = _pieces(out_single)
    assert want

    wport = _free_port()
    coord = f"127.0.0.1:{_free_port()}"
    wdir = tmp_path / "slicehost"
    wdir.mkdir()
    wmodel = str(wdir / "model.bin")
    root = _run_n("inference", model, tok, 0, coord, 2, 1, cwd, tp=2,
                  extra=gen + ("--serve-weights", str(wport)))
    worker = _run_n("worker", wmodel, tok, 1, coord, 2, 1, cwd, tp=2,
                    extra=gen + ("--model-from-root", f"127.0.0.1:{wport}",
                                 "--stream-slices"))
    out_root, err_root = root.communicate(timeout=420)
    out_worker, err_worker = worker.communicate(timeout=60)
    assert root.returncode == 0, f"root: {err_root[-2000:]}"
    assert worker.returncode == 0, f"worker: {err_worker[-2000:]}"
    assert _pieces(out_root) == want, out_root

    import json as _json
    import os as _os

    assert _os.path.getsize(wmodel) == _os.path.getsize(model)  # sparse full
    with open(wmodel + ".slices") as fh:
        meta = _json.load(fh)
    fetched = sum(ln for _, ln in meta["ranges"])
    # exactly the host's needed ranges: header + replicated tensors in full
    # + HALF of every matmul tensor's bytes (rank 1 of tp=2) — in this tiny
    # spec the replicated embedding dominates, so assert the exact sum, not
    # a fraction (at 70B the matmul share is ~97% and this IS ~1/tp)
    from distributed_llama_tpu.io.loader import tensor_byte_ranges
    from distributed_llama_tpu.io.stream import needed_byte_ranges

    want_bytes = sum(ln for _, ln in needed_byte_ranges(SPEC, 2, {1}))
    assert fetched == want_bytes, meta
    matmul = sum(tr.nbytes for tr in tensor_byte_ranges(SPEC)
                 if tr.rows is not None)
    assert fetched <= _os.path.getsize(model) - matmul // 2  # a real cut
