"""Telemetry subsystem: metric math, registry thread-safety, Prometheus
exposition, engine lifecycle tracing, and the off-unless-enabled contract
(a disabled engine makes ZERO registry calls on the hot path)."""

import json
import threading
import urllib.request

import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.obs.metrics import (Counter, Gauge, Histogram,
                                               Registry, summarize_values)

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


class _IdTokenizer:
    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"<%d>" % tok


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


# ---------------------------------------------------------------- metrics


def test_histogram_bucket_and_percentile_math():
    h = Histogram("h", buckets=(1.0, 2.0, 4.0, 8.0))
    for v in (0.5, 1.5, 1.5, 3.0, 7.0, 100.0):
        h.observe(v)
    counts, s, total = h.snapshot()
    assert counts == [1, 2, 1, 1, 1]  # per-bucket, +Inf last
    assert total == 6 and s == pytest.approx(113.5)
    # p50: rank 3 of 6 -> second bucket (1, 2]: 1 + (3-1)/2 * 1 = 2.0
    assert h.percentile(0.50) == pytest.approx(2.0)
    # p100 lands in +Inf: clamps to the last finite bound
    assert h.percentile(1.0) == pytest.approx(8.0)
    # empty histogram: all zeros
    assert Histogram("e", buckets=(1.0,)).percentile(0.9) == 0.0
    summ = h.summary()
    assert summ["count"] == 6
    assert summ["mean"] == pytest.approx(113.5 / 6)


def test_histogram_rejects_unsorted_buckets():
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(2.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(1.0, 1.0))


def test_summarize_values_matches_percentile_semantics():
    s = summarize_values(range(1, 101))  # 1..100
    assert s["count"] == 100
    assert s["mean"] == pytest.approx(50.5)
    assert s["p50"] == pytest.approx(50.5)
    assert s["p99"] == pytest.approx(99.01)
    assert summarize_values([])["p95"] == 0.0
    # unit_scale rescales on the way in (ms list -> seconds)
    assert summarize_values([1000.0], unit_scale=1e-3)["p50"] == 1.0


def test_registry_get_or_create_and_mismatch():
    reg = Registry()
    c1 = reg.counter("c", "help")
    assert reg.counter("c") is c1
    with pytest.raises(ValueError):
        reg.gauge("c")
    h = reg.histogram("h", buckets=(1.0, 2.0))
    assert reg.histogram("h", buckets=(1.0, 2.0)) is h
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=(1.0, 3.0))


def test_registry_thread_safety_exact_counts():
    reg = Registry()
    c = reg.counter("dllama_test_total")
    g = reg.gauge("dllama_test_gauge")
    h = reg.histogram("dllama_test_seconds", buckets=(0.5, 1.5))
    N, T = 2000, 8

    def writer():
        for i in range(N):
            c.inc()
            g.inc()
            h.observe(i % 2)

    threads = [threading.Thread(target=writer) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == N * T
    assert g.value == N * T
    counts, s, total = h.snapshot()
    assert total == N * T
    assert counts == [N * T // 2, N * T // 2, 0]
    assert s == pytest.approx(N * T // 2)


def test_counter_rejects_negative():
    with pytest.raises(ValueError):
        Counter("c").inc(-1)


def test_exposition_format_golden():
    reg = Registry()
    reg.counter("dllama_generated_tokens_total", "Tokens emitted").inc(7)
    g = reg.gauge("dllama_active_slots", "Active now")
    g.set(2.5)
    h = reg.histogram("dllama_ttft_seconds", "TTFT",
                      buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(3.0)
    assert reg.expose() == (
        "# HELP dllama_generated_tokens_total Tokens emitted\n"
        "# TYPE dllama_generated_tokens_total counter\n"
        "dllama_generated_tokens_total 7\n"
        "# HELP dllama_active_slots Active now\n"
        "# TYPE dllama_active_slots gauge\n"
        "dllama_active_slots 2.5\n"
        "# HELP dllama_ttft_seconds TTFT\n"
        "# TYPE dllama_ttft_seconds histogram\n"
        'dllama_ttft_seconds_bucket{le="0.1"} 1\n'
        'dllama_ttft_seconds_bucket{le="1"} 2\n'
        'dllama_ttft_seconds_bucket{le="+Inf"} 3\n'
        "dllama_ttft_seconds_sum 3.55\n"
        "dllama_ttft_seconds_count 3\n")


# ------------------------------------------------------------ event log


def test_log_event_json_and_text_modes(capsys, monkeypatch):
    from distributed_llama_tpu.obs.log import log_event

    monkeypatch.delenv("DLLAMA_LOG_JSON", raising=False)
    log_event("x", "human line", field=1)
    assert capsys.readouterr().out == "human line\n"

    monkeypatch.setenv("DLLAMA_LOG_JSON", "1")
    log_event("decode.token", "human line", pos=3, gen_ms=1.5)
    rec = json.loads(capsys.readouterr().out)
    assert rec["event"] == "decode.token"
    assert rec["pos"] == 3 and rec["gen_ms"] == 1.5
    assert "ts" in rec

    # text=None: JSON-only event, silent in human mode
    monkeypatch.delenv("DLLAMA_LOG_JSON", raising=False)
    log_event("run.summary", None, tokens=5)
    assert capsys.readouterr().out == ""


# -------------------------------------------------- engine lifecycle


def _patch_instrument_calls(monkeypatch):
    """Wrap every registry-instrument mutator with a call counter."""
    calls = []

    def wrap(cls, name):
        orig = getattr(cls, name)

        def spy(self, *a, **kw):
            calls.append((cls.__name__, name))
            return orig(self, *a, **kw)

        monkeypatch.setattr(cls, name, spy)

    wrap(Counter, "inc")
    wrap(Gauge, "set")
    wrap(Gauge, "inc")
    wrap(Gauge, "dec")
    wrap(Histogram, "observe")
    return calls


def test_engine_zero_registry_calls_when_disabled(params, monkeypatch):
    """The acceptance gate: metrics collection is OFF the hot path unless
    enabled — an engine built without a registry must not touch any
    instrument during submit/step/retire."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    calls = _patch_instrument_calls(monkeypatch)
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5)
    outs, _ = eng.run([[1, 5, 9], [1, 7]], steps=8)
    assert all(outs)
    assert calls == []


def test_engine_lifecycle_metrics_populated(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg)
    outs, stats = eng.run([[1, 5, 9], [1, 7], [1, 2]], steps=8)
    assert reg.get("dllama_requests_total").value == 3
    assert reg.get("dllama_request_ttft_seconds").count == 3
    assert reg.get("dllama_request_queue_wait_seconds").count == 3
    assert reg.get("dllama_request_decode_token_seconds").count == 3
    assert reg.get("dllama_generated_tokens_total").value == stats.tokens
    assert reg.get("dllama_engine_steps_total").value == stats.steps
    assert reg.get("dllama_engine_step_duration_seconds").count > 0
    occ = reg.get("dllama_engine_batch_occupancy")
    assert occ.count > 0
    # queue drained at the end
    assert reg.get("dllama_engine_queued_requests").value == 0


def test_paged_engine_exports_page_and_prefix_series(params):
    """ISSUE 6 satellite: a paged engine moves dllama_kv_pages_free and
    dllama_prefix_hits_total, and both land in the Prometheus exposition
    with their HELP/TYPE headers."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    sys_p = [1] + list(range(20, 28))  # 2 full pages at page_size=4
    reqs = [sys_p + [40 + i] for i in range(4)]
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg, page_size=4,
                           prefill_chunk=4)
    eng.run(reqs, steps=12)
    a = eng.allocator
    assert reg.get("dllama_prefix_hits_total").value == a.prefix_hits > 0
    assert reg.get("dllama_prefill_tokens_saved_total").value \
        == a.tokens_saved > 0
    # after the drain: every page is free or idle in the radix tree
    assert reg.get("dllama_kv_pages_free").value == a.n_free
    text = reg.expose()
    for family, kind in (("dllama_kv_pages_free", "gauge"),
                         ("dllama_prefix_hits_total", "counter"),
                         ("dllama_prefill_tokens_saved_total", "counter")):
        assert f"# TYPE {family} {kind}" in text
        assert f"# HELP {family} " in text


def test_contiguous_engine_page_series_stay_zero(params):
    """The paged instruments exist on every engine (layout-invariant
    scrape surface) but a contiguous engine never moves them."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg)
    eng.run([[1, 5, 9], [1, 7]], steps=8)
    assert eng.allocator is None
    assert reg.get("dllama_kv_pages_free").value == 0
    assert reg.get("dllama_prefix_hits_total").value == 0
    assert "dllama_kv_pages_free 0" in reg.expose()


def test_spec_engine_exports_proposed_and_accepted_series(params):
    """ISSUE 7 satellite: a speculative engine moves
    dllama_spec_proposed_total / dllama_spec_accepted_total, pinned equal
    to the engine's own stats counters, and both land in the exposition
    with HELP/TYPE headers."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg, page_size=4,
                           spec_k=4)
    _, st = eng.run([[1, 5, 9], [1, 22], [1, 7, 33]], steps=10)
    assert reg.get("dllama_spec_proposed_total").value \
        == st.spec_proposed > 0
    assert reg.get("dllama_spec_accepted_total").value == st.spec_accepted
    assert st.spec_accepted <= st.spec_proposed
    text = reg.expose()
    for family in ("dllama_spec_proposed_total",
                   "dllama_spec_accepted_total"):
        assert f"# TYPE {family} counter" in text
        assert f"# HELP {family} " in text


def test_plain_engine_spec_series_stay_zero(params):
    """Spec instruments exist on every engine but never move when
    spec_k == 0 — dashboards survive the knob."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg, page_size=4)
    _, st = eng.run([[1, 5, 9]], steps=8)
    assert eng.spec_k == 0 and st.spec_proposed == 0
    assert reg.get("dllama_spec_proposed_total").value == 0
    assert "dllama_spec_proposed_total 0" in reg.expose()


def test_admission_pressure_series_exposed_at_zero(params):
    """ISSUE 8 satellite: dllama_queue_depth, dllama_slot_pauses_total,
    and the full dllama_admission_rejected_total{reason} matrix are
    registered at engine creation — a fresh scrape shows them all at
    zero, one HELP/TYPE header per family."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                     seed=5, metrics=reg)
    text = reg.expose()
    assert "# TYPE dllama_queue_depth gauge" in text
    assert "dllama_queue_depth 0" in text
    assert "# TYPE dllama_slot_pauses_total counter" in text
    assert "dllama_slot_pauses_total 0" in text
    assert text.count("# TYPE dllama_admission_rejected_total counter") == 1
    for reason in ("pool_dry", "deadlock", "oversized", "bad_request"):
        assert (f'dllama_admission_rejected_total{{reason="{reason}"}} 0'
                in text)


def test_queue_depth_tracks_legacy_gauge(params):
    """dllama_queue_depth (the ISSUE-8 canonical name) and the legacy
    dllama_engine_queued_requests are written together and can never
    diverge."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg)
    eng.submit(Request(tokens=[1, 5], steps=4))
    eng.submit(Request(tokens=[1, 7], steps=4))
    assert reg.get("dllama_queue_depth").value == 2
    assert reg.get("dllama_engine_queued_requests").value == 2
    while eng.step_once():
        pass
    assert reg.get("dllama_queue_depth").value == 0
    assert reg.get("dllama_engine_queued_requests").value == 0


def test_pool_dry_requeue_moves_reject_counter_and_pauses(params):
    """Transient page starvation (chaos denial) exercises the dry-pool
    admission path: the head-of-queue requeue counts under
    admission_rejected{reason="pool_dry"}, pinned to stats.requeues."""
    from distributed_llama_tpu.runtime.chaos import ChaosMonkey
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg, page_size=4,
                           chaos=ChaosMonkey(deny_pages=2))
    outs, st = eng.run([[1, 5, 9]], steps=8)
    assert outs[0]  # the request completed once the denials ran out
    assert st.requeues >= 1
    assert reg.get('dllama_admission_rejected_total'
                   '{reason="pool_dry"}').value == st.requeues


def test_page_starved_slot_pause_counts(params):
    """A slot pausing for pages (pool oversubscribed, other slots still
    runnable) moves dllama_slot_pauses_total in step with stats.pauses."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    # 3 slots over a 5-page pool at page_size 4: the third request's
    # growth finds the pool dry while the other two keep decoding, so it
    # pauses (not a deadlock — len(paused) < active) until a retirement
    # frees pages
    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=3, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg, page_size=4,
                           kv_pages=5, prefix_share=False)
    reqs = [[1, 5, 9], [1, 7, 11], [1, 6, 13]]
    outs, st = eng.run(reqs, steps=12)
    assert all(outs)
    assert st.pauses > 0
    assert reg.get("dllama_slot_pauses_total").value == st.pauses


def test_server_health_reports_spec_accept_rate(params):
    """ISSUE 7 satellite: /health carries the speculative block (k,
    proposed, accepted, accept_rate) when --spec-k is on."""
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, page_size=4, spec_k=4)
    srv.start()
    try:
        _post(srv.port, "/generate", {"prompt": "xyx", "steps": 6})
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=30) as r:
            h = json.loads(r.read())
        sp = h["speculative"]
        assert sp["k"] == 4
        assert sp["accepted"] <= sp["proposed"]
        assert 0.0 <= sp["accept_rate"] <= 1.0
        assert sp["accept_rate"] == round(
            sp["accepted"] / max(sp["proposed"], 1), 4)
    finally:
        srv.stop()


def test_engine_compile_event_counter(params):
    """Fused-chain shape-cache misses count as compile events; reusing a
    chain shape does not."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, block_steps=3, metrics=reg)
    eng.run([[1, 5]], steps=6)
    first = reg.get("dllama_engine_compile_events_total").value
    assert first >= 1
    eng.run([[1, 7]], steps=6)  # same chain shape: no new trace
    assert reg.get("dllama_engine_compile_events_total").value == first


# ---------------------------------------------------- server round-trip


@pytest.fixture()
def server(params):
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)
    srv.start()
    yield srv
    srv.stop()


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def test_server_metrics_round_trip(server):
    """/metrics after a /generate: valid Prometheus text whose values are
    consistent with the completed request (the acceptance criterion)."""
    r = _post(server.port, "/generate", {"prompt": "ab", "steps": 8})
    n_tokens = len(r["tokens"])
    assert n_tokens > 0

    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=30) as resp:
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()

    metrics = {}
    for line in text.splitlines():
        assert line, "blank line in exposition"
        if line.startswith("#"):
            assert line.startswith(("# HELP ", "# TYPE "))
            continue
        name_part, value = line.rsplit(" ", 1)
        metrics[name_part] = float(value)
    assert metrics["dllama_request_ttft_seconds_count"] == 1
    assert metrics["dllama_request_queue_wait_seconds_count"] == 1
    assert metrics["dllama_generated_tokens_total"] == n_tokens
    assert metrics["dllama_engine_step_duration_seconds_count"] >= 1
    assert metrics["dllama_requests_total"] == 1
    # cumulative bucket invariant: +Inf bucket == count
    assert metrics['dllama_request_ttft_seconds_bucket{le="+Inf"}'] \
        == metrics["dllama_request_ttft_seconds_count"]


def test_server_health_enriched(server):
    _post(server.port, "/generate", {"prompt": "x", "steps": 4})
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/health", timeout=30) as r:
        h = json.loads(r.read())
    assert h["slots"] == 2
    assert h["uptime_s"] > 0
    assert 0.0 <= h["occupancy"] <= 1.0
    for key in ("ttft_s", "token_latency_s", "queue_wait_s"):
        assert h[key]["count"] >= 1
        assert h[key]["p50"] <= h[key]["p95"] <= h[key]["p99"]


def test_server_no_metrics_disables_endpoint(params):
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=1, steps=4, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, metrics=False)
    srv.start()
    try:
        assert srv.engine._obs is None
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=30)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # /health still serves its engine-level fields
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=30) as r:
            h = json.loads(r.read())
        assert "ttft_s" not in h and h["slots"] == 1
    finally:
        srv.stop()


def test_server_profile_endpoint(server, tmp_path):
    from distributed_llama_tpu.obs import profiler

    d = str(tmp_path / "trace")
    out = _post(server.port, "/profile", {"seconds": 0.2, "dir": d})
    assert out == {"dir": d, "seconds": 0.2}
    # a second capture while one is running -> 409
    try:
        _post(server.port, "/profile", {"seconds": 0.2, "dir": d})
        overlapped = False
    except urllib.error.HTTPError as e:
        assert e.code == 409
        overlapped = True
    assert profiler.wait_capture(30)
    assert overlapped or profiler.capture_active() is None
    # bad duration -> 400
    try:
        _post(server.port, "/profile", {"seconds": -1})
        assert False, "expected 400"
    except urllib.error.HTTPError as e:
        assert e.code == 400


# ------------------------------------------- flash-degrade warning


def test_explicit_flash_degrade_warns_once(monkeypatch, capsys):
    """DLLAMA_PREFILL_ATTN=flash degrading to the blockwise walk must say
    so loudly, once (the fail-loud policy for explicit modes)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models import llama

    monkeypatch.setenv("DLLAMA_PREFILL_ATTN", "flash")
    monkeypatch.setenv("DLLAMA_ATTN_KERNEL", "xla")  # kernel unavailable
    monkeypatch.setattr(llama, "_flash_degrade_warned", False)
    t_len = 16
    q = jnp.zeros((t_len, SPEC.n_heads, SPEC.head_size))
    k = jnp.zeros((SPEC.seq_len, SPEC.n_kv_heads, SPEC.head_size))
    v = jnp.zeros_like(k)
    llama.attention(SPEC, q, k, v, jnp.int32(0), t_len)
    err = capsys.readouterr().err
    assert "DLLAMA_PREFILL_ATTN=flash" in err
    assert "blockwise" in err
    llama.attention(SPEC, q, k, v, jnp.int32(0), t_len)
    assert "DLLAMA_PREFILL_ATTN" not in capsys.readouterr().err  # once


# --------------------------------------- labeled series + collective gauges


def test_labeled_counter_exposition_and_family_grouping():
    reg = Registry()
    a = reg.labeled_counter("dllama_ici_collectives_total",
                            {"kind": "psum", "scheme": "fused"}, "Launches")
    b = reg.labeled_counter("dllama_ici_collectives_total",
                            {"kind": "all_gather", "scheme": "fused"})
    a.inc(3)
    b.inc(1)
    # same (name, labels) -> the same series; different labels -> distinct
    assert reg.labeled_counter("dllama_ici_collectives_total",
                               {"kind": "psum", "scheme": "fused"}) is a
    assert a is not b
    text = reg.expose()
    assert text.count("# TYPE dllama_ici_collectives_total counter") == 1
    assert ('dllama_ici_collectives_total{kind="psum",scheme="fused"} 3'
            in text)
    assert ('dllama_ici_collectives_total{kind="all_gather",scheme="fused"}'
            ' 1' in text)
    assert reg.get(
        'dllama_ici_collectives_total{kind="psum",scheme="fused"}') is a


def test_interleaved_registration_still_groups_families():
    """bind_collectives registers (launches, bytes) PAIRWISE per kind;
    the exposition must still emit each family as ONE contiguous group
    under a single header (the Prometheus grouping rule — interleaved
    families parse as duplicate untyped ones)."""
    reg = Registry()
    reg.labeled_counter("dllama_ici_collectives_total",
                        {"kind": "psum"}, "launches").inc(2)
    reg.labeled_counter("dllama_ici_bytes_total",
                        {"kind": "psum"}, "bytes").inc(10)
    reg.labeled_counter("dllama_ici_collectives_total",
                        {"kind": "all_gather"}).inc(1)
    reg.labeled_counter("dllama_ici_bytes_total",
                        {"kind": "all_gather"}).inc(5)
    lines = reg.expose().splitlines()
    series_families = [ln.split("{")[0] for ln in lines
                       if not ln.startswith("#")]
    assert series_families == ["dllama_ici_collectives_total"] * 2 + \
        ["dllama_ici_bytes_total"] * 2
    assert lines[0].startswith("# HELP dllama_ici_collectives_total")


def test_labeled_series_kind_mismatch_raises():
    reg = Registry()
    reg.labeled_counter("m", {"k": "v"})
    with pytest.raises(ValueError):
        reg.labeled_gauge("m", {"k": "v"})
    # kind is a FAMILY property: a differently-labeled (or unlabeled)
    # series cannot smuggle a second kind under the same name — it would
    # expose under the wrong TYPE header
    with pytest.raises(ValueError):
        reg.labeled_gauge("m", {"k": "other"})
    reg.counter("plain")
    with pytest.raises(ValueError):
        reg.labeled_gauge("plain", {"k": "v"})


def test_label_order_does_not_split_series():
    """The label SET is the series identity: two call sites passing the
    same labels in different key order must land on one series (and one
    exposition line — duplicates fail a Prometheus scrape)."""
    reg = Registry()
    a = reg.labeled_counter("m", {"kind": "psum", "scheme": "fused"})
    b = reg.labeled_counter("m", {"scheme": "fused", "kind": "psum"})
    assert a is b
    a.inc(2)
    assert reg.expose().count('m{kind="psum",scheme="fused"}') == 1


def test_engine_metrics_collective_gauges_track_steps():
    """bind_collectives turns the analytic schedule into labeled series:
    N launches and rows*bytes per device step, per kind."""
    from distributed_llama_tpu.models.synth import llama2_7b_spec
    from distributed_llama_tpu.obs.trace import EngineMetrics
    from distributed_llama_tpu.parallel.comm_stats import tp_collective_budget

    reg = Registry()
    em = EngineMetrics(reg)
    budget = tp_collective_budget(llama2_7b_spec(), 8, "fused")
    em.bind_collectives(budget, "fused", rows=4)
    em.record_step(0.01, active=2, steps=3)
    counts = budget.kind_counts()
    by_kind = budget.bytes_by_kind()
    for kind in counts:
        launches = reg.get(f'dllama_ici_collectives_total'
                           f'{{kind="{kind}",scheme="fused"}}')
        moved = reg.get(f'dllama_ici_bytes_total'
                        f'{{kind="{kind}",scheme="fused"}}')
        assert launches.value == counts[kind] * 3
        assert moved.value == by_kind[kind] * 4 * 3


def test_sharded_engine_exports_collective_gauges(params):
    """A tp>1 engine with metrics on exposes the budget series on its
    registry — the /metrics surface the drift gate checks against."""
    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.parallel.comm_stats import (
        tp_collective_budget, tp_scheme)
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, mesh=make_mesh(tp=2),
                           metrics=reg)
    eng.run([[1, 5, 9]], steps=6)
    scheme = tp_scheme()
    budget = tp_collective_budget(SPEC, 2, scheme)
    assert budget.entries, "tp=2 must have a collective budget"
    steps = reg.get("dllama_engine_steps_total").value
    for kind, count, moved in budget.entries:
        launches = reg.get(f'dllama_ici_collectives_total'
                           f'{{kind="{kind}",scheme="{scheme}"}}')
        assert launches is not None, f"missing series for {kind}"
        assert launches.value == count * steps
        moved_c = reg.get(f'dllama_ici_bytes_total'
                          f'{{kind="{kind}",scheme="{scheme}"}}')
        # bytes scale by the slot count: each batched collective moves
        # B rows whether or not every slot is occupied
        assert moved_c.value == moved * eng.slots * steps
    assert 'dllama_ici_collectives_total{kind=' in reg.expose()


def test_unsharded_engine_has_no_collective_series(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reg = Registry()
    ContinuousEngine(SPEC, params, slots=1, temperature=0.0, topp=0.9,
                     seed=5, metrics=reg)
    assert "dllama_ici_collectives_total" not in reg.expose()


# --------------------------------------------------- NDJSON run stamp


def test_log_json_records_carry_run_stamp(capsys, monkeypatch):
    """Every NDJSON record carries tp_scheme + the Q40 body policy + the
    bench env_fingerprint, so log streams join against BENCH_* rows."""
    from distributed_llama_tpu.obs.log import log_event
    from distributed_llama_tpu.utils import fingerprint

    monkeypatch.setenv("DLLAMA_LOG_JSON", "1")
    monkeypatch.setenv("DLLAMA_TP_SCHEME", "ref")
    monkeypatch.setenv("DLLAMA_Q40_BODY", "i4-nb")
    fingerprint.reset_stamp_cache()
    try:
        log_event("decode.token", None, pos=1)
        rec = json.loads(capsys.readouterr().out)
        assert rec["tp_scheme"] == "ref"
        assert rec["q40_body"] == "i4-nb"
        assert "clock" in rec["env_fingerprint"]
        # jax is imported under the test env: the fingerprint pins the
        # session basis the same way bench rows do
        assert rec["env_fingerprint"]["backend"] == "cpu"
        assert rec["pos"] == 1
    finally:
        fingerprint.reset_stamp_cache()  # drop the env-specific stamp


def test_log_stamp_knobs_read_fresh_per_record(capsys, monkeypatch):
    """A --model-from-root run logs BEFORE cli.py exports --tp-scheme:
    the knob fields must track the env per record, never freeze at the
    first event's values."""
    from distributed_llama_tpu.obs.log import log_event

    monkeypatch.setenv("DLLAMA_LOG_JSON", "1")
    monkeypatch.delenv("DLLAMA_TP_SCHEME", raising=False)
    log_event("weights.fetch_progress", None)  # early event, default env
    first = json.loads(capsys.readouterr().out)
    assert first["tp_scheme"] == "fused"
    monkeypatch.setenv("DLLAMA_TP_SCHEME", "ref")  # cli.py applies the flag
    log_event("decode.token", None)
    assert json.loads(capsys.readouterr().out)["tp_scheme"] == "ref"


def test_log_stamp_survives_bad_scheme_env(capsys, monkeypatch):
    """A malformed DLLAMA_TP_SCHEME must degrade the stamp, not take the
    log line (or its caller) down."""
    from distributed_llama_tpu.obs.log import log_event
    from distributed_llama_tpu.utils import fingerprint

    monkeypatch.setenv("DLLAMA_LOG_JSON", "1")
    monkeypatch.setenv("DLLAMA_TP_SCHEME", "bogus")
    fingerprint.reset_stamp_cache()
    try:
        log_event("x", None, n=1)
        rec = json.loads(capsys.readouterr().out)
        assert rec["tp_scheme"] == "bogus"  # reported verbatim, not raised
        assert rec["n"] == 1
    finally:
        fingerprint.reset_stamp_cache()


def test_bench_fingerprint_is_the_shared_one():
    """bench.py and the log stamp must report the SAME fingerprint dict —
    joinability means one producer, not two drifting copies."""
    import bench

    from distributed_llama_tpu.utils.fingerprint import env_fingerprint

    assert bench._env_fingerprint() == env_fingerprint()


# ------------------------------------------- profiler error paths


def test_profiler_unwritable_dir_fails_clean(tmp_path):
    """An uncreatable trace dir raises BEFORE the capture starts: the
    singleton stays free and a later capture into a good dir works."""
    from distributed_llama_tpu.obs import profiler

    blocker = tmp_path / "file"
    blocker.write_text("not a dir")
    bad = str(blocker / "sub")  # a path THROUGH a file: mkdir must fail
    with pytest.raises(OSError):
        profiler.start_capture(bad, 1.0)
    assert profiler.capture_active() is None
    good = str(tmp_path / "ok")
    profiler.start_capture(good, 0.2)
    assert profiler.capture_active() == good
    assert profiler.wait_capture(30)


def test_server_profile_409_and_500_paths(server, tmp_path):
    """Deterministic overlap: start a capture directly, then POST — the
    server must answer 409 while it runs and 500 for an unwritable
    DLLAMA_PROFILE_DIR-style target, then recover."""
    from distributed_llama_tpu.obs import profiler

    d = str(tmp_path / "held")
    profiler.start_capture(d, 0.5)
    try:
        _post(server.port, "/profile", {"seconds": 0.1,
                                        "dir": str(tmp_path / "x")})
        assert False, "expected 409"
    except urllib.error.HTTPError as e:
        assert e.code == 409
    assert profiler.wait_capture(30)

    blocker = tmp_path / "plainfile"
    blocker.write_text("x")
    try:
        _post(server.port, "/profile", {"seconds": 0.1,
                                        "dir": str(blocker / "sub")})
        assert False, "expected 500"
    except urllib.error.HTTPError as e:
        assert e.code == 500
        assert "trace dir" in json.loads(e.read())["error"]
    # the failed request must not wedge the singleton
    assert profiler.capture_active() is None
    out = _post(server.port, "/profile",
                {"seconds": 0.2, "dir": str(tmp_path / "after")})
    assert out["seconds"] == 0.2
    assert profiler.wait_capture(30)


# ------------------------------------------------- disaggregation (ISSUE 14)


def test_disagg_series_preregistered_at_zero():
    """ISSUE 14 satellite: DisaggMetrics pre-registers the whole handoff
    matrix at zero — a fresh prefill/decode pool scrapes the full
    surface before any request moves."""
    from distributed_llama_tpu.runtime.disagg import DisaggMetrics

    reg = Registry()
    DisaggMetrics(reg)
    text = reg.expose()
    for verdict in ("shipped", "local", "failed"):
        assert (f'dllama_handoff_requests_total{{verdict="{verdict}"}} 0'
                in text)
    assert "dllama_dcn_pages_shipped_total 0" in text
    assert "dllama_dcn_bytes_total 0" in text
    assert "dllama_handoff_queue_depth 0" in text
    assert "dllama_handoff_seconds_count 0" in text
    for family, kind in (
            ("dllama_handoff_requests_total", "counter"),
            ("dllama_dcn_pages_shipped_total", "counter"),
            ("dllama_dcn_bytes_total", "counter"),
            ("dllama_handoff_queue_depth", "gauge"),
            ("dllama_handoff_seconds", "histogram")):
        assert f"# TYPE {family} {kind}" in text
        assert f"# HELP {family} " in text


def test_disagg_handoff_moves_series_and_health_block(params):
    """A real two-pool handoff moves the dllama_dcn_* series (pages AND
    payload bytes pinned to the DCN budget's numbers), and /health on a
    disaggregated server carries the "disagg" block."""
    import json
    import urllib.request

    from distributed_llama_tpu.parallel.comm_stats import \
        dcn_handoff_budget
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine
    from distributed_llama_tpu.runtime.disagg import DisaggPair
    from distributed_llama_tpu.runtime.server import InferenceServer

    reg = Registry()
    make = lambda remote=False: ContinuousEngine(  # noqa: E731
        SPEC, params, slots=2, temperature=0.0, topp=0.9, seed=11,
        prefill_chunk=4, page_size=4, kv_pages=16, remote_pages=remote)
    pair = DisaggPair(make(), make(remote=True), registry=reg)
    prompt = [1, 9, 17, 25, 31, 7, 3, 44, 11]
    pair.run([prompt], steps=14)
    text = reg.expose()
    budget = dcn_handoff_budget(SPEC, 1, len(prompt) - 1, 4)
    assert f"dllama_dcn_pages_shipped_total {budget['pages']}" in text
    assert f"dllama_dcn_bytes_total {budget['bytes']}" in text
    assert 'dllama_handoff_requests_total{verdict="shipped"} 1' in text
    pair.close()

    server = InferenceServer(SPEC, params, _IdTokenizer(),
                             host="127.0.0.1", port=0, slots=2, steps=8,
                             temperature=0.0, topp=0.9, seed=3,
                             page_size=4, kv_pages=16,
                             disagg_role="prefill", quiet=True)
    server.start()
    try:
        health = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/health", timeout=10).read())
        assert health["disagg"]["role"] == "prefill"
        assert health["disagg"]["handoff_queue_depth"] == 0
        assert health["disagg"]["page_channel_port"] > 0
        metrics = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics",
            timeout=10).read().decode()
        assert "dllama_dcn_pages_shipped_total 0" in metrics
    finally:
        server.stop()
