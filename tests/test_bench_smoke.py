"""bench.py driver-protocol smoke: the default `python bench.py` run must
emit ONE final JSON line whose payload carries every config row (the
artifact the driver parses into BENCH_r{N}.json — VERDICT r2 #1).

Runs the aggregation over the tiny config only (DLLAMA_BENCH_CONFIGS=small,
the documented test hook) on the CPU backend; the real 7b/13b/70b-tp8 rows
are exercised on hardware."""

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_all_emits_one_json_line_with_rows(tmp_path):
    # strip the axon sitecustomize from the child's path: it force-sets
    # jax_platforms='axon,cpu' as explicit config at interpreter start
    # (conftest.py header), which would override JAX_PLATFORMS=cpu and
    # dial the TPU tunnel from what must stay a CPU smoke run
    pypath = os.pathsep.join(
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    env = {**os.environ,
           "PYTHONPATH": pypath,
           "DLLAMA_BENCH_CONFIGS": "small",
           "DLLAMA_JAX_CACHE_DIR": str(tmp_path / "cache"),
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--samples", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        timeout=900, cwd=_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["unit"] == "ms/token"
    assert payload["value"] > 0
    assert "small" in payload["rows"]
    row = payload["rows"]["small"]
    assert row["value"] > 0 and row["executed"] >= 1
    assert "startup_to_first_token_s" in row


def test_scaling_curve_assembly():
    """_scaling_curve (VERDICT r3 #2) mirrors the reference's per-device-
    count table: tp=1 from the measured single-chip row, tp>1 from the
    rank rows, same-n reference baselines, per-point kv_cache basis;
    missing/failed rows are skipped, empty rows give an empty curve."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    rows = {"7b": {"value": 9.8, "kv_cache": "f32"},
            "13b": {"value": 17.9, "kv_cache": "bf16"},
            "7b-tp2": {"value": 6.36, "kv_cache": "f32",
                       "shard_ms_measured": 6.22,
                       "ici_bandwidth_ms_modeled": 0.017,
                       "ici_latency_ms_modeled": 0.129},
            "13b-tp8": {"value": 6.6, "kv_cache": "f32",
                        "shard_ms_measured": 5.43,
                        "ici_bandwidth_ms_modeled": 0.047,
                        "ici_latency_ms_modeled": 1.127},
            "13b-tp4": {"error": "rc=1"},  # failed row: skipped
            "70b-tp8": {"value": 18.9}}    # not part of the curve
    curve = bench._scaling_curve(rows)
    assert set(curve) == {"7b", "13b"}
    assert curve["7b"]["1"]["reference_ms"] == 1312.50
    assert curve["7b"]["1"]["vs_reference_same_n"] == round(1312.50 / 9.8, 2)
    assert curve["7b"]["2"]["reference_ms"] == 793.69
    assert curve["7b"]["2"]["shard_ms_measured"] == 6.22
    # 13B has no published 1-device row; the measured point still appears
    assert curve["13b"]["1"]["reference_ms"] is None
    assert curve["13b"]["1"]["kv_cache"] == "bf16"
    assert curve["13b"]["8"]["vs_reference_same_n"] == round(1114.88 / 6.6, 2)
    assert "4" not in curve["13b"]  # failed row skipped
    assert bench._scaling_curve({}) == {}
    # _BASE scaling baselines derive from the same table (one source of
    # truth): spot-check through the public surface
    assert bench._REF_CURVE["13b"][4] == 848.19
