"""bench.py driver-protocol smoke: the default `python bench.py` run must
emit ONE final JSON line whose payload carries every config row (the
artifact the driver parses into BENCH_r{N}.json — VERDICT r2 #1).

Runs the aggregation over the tiny config only (DLLAMA_BENCH_CONFIGS=small,
the documented test hook) on the CPU backend; the real 7b/13b/70b-tp8 rows
are exercised on hardware."""

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_all_emits_one_json_line_with_rows(tmp_path):
    # strip the axon sitecustomize from the child's path: it force-sets
    # jax_platforms='axon,cpu' as explicit config at interpreter start
    # (conftest.py header), which would override JAX_PLATFORMS=cpu and
    # dial the TPU tunnel from what must stay a CPU smoke run
    pypath = os.pathsep.join(
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    env = {**os.environ,
           "PYTHONPATH": pypath,
           "DLLAMA_BENCH_CONFIGS": "small",
           "DLLAMA_JAX_CACHE_DIR": str(tmp_path / "cache"),
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--samples", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        timeout=900, cwd=_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["unit"] == "ms/token"
    assert payload["value"] > 0
    assert "small" in payload["rows"]
    row = payload["rows"]["small"]
    assert row["value"] > 0 and row["executed"] >= 1
    assert "startup_to_first_token_s" in row
