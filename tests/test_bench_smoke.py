"""bench.py driver-protocol smoke: the default `python bench.py` run must
emit ONE final JSON line whose payload carries every config row (the
artifact the driver parses into BENCH_r{N}.json — VERDICT r2 #1).

Runs the aggregation over the tiny config only (DLLAMA_BENCH_CONFIGS=small,
the documented test hook) on the CPU backend; the real 7b/13b/70b-tp8 rows
are exercised on hardware."""

import json
import os
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_all_emits_one_json_line_with_rows(tmp_path):
    # strip the axon sitecustomize from the child's path: it force-sets
    # jax_platforms='axon,cpu' as explicit config at interpreter start
    # (conftest.py header), which would override JAX_PLATFORMS=cpu and
    # dial the TPU tunnel from what must stay a CPU smoke run
    pypath = os.pathsep.join(
        p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon" not in p)
    full_path = tmp_path / "BENCH_FULL.json"
    env = {**os.environ,
           "PYTHONPATH": pypath,
           "DLLAMA_BENCH_CONFIGS": "small",
           "DLLAMA_BENCH_FULL_PATH": str(full_path),
           "DLLAMA_JAX_CACHE_DIR": str(tmp_path / "cache"),
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--samples", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        timeout=900, cwd=_ROOT)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    # the VERDICT r4 #1 regression guard: round 4's stdout line outgrew the
    # driver protocol's capture (truncated mid-JSON at 2000 chars ->
    # parsed=null); the compact line must stay WELL inside that budget
    assert len(line) < 1800, f"compact line too long ({len(line)} chars)"
    payload = json.loads(line)
    assert payload["unit"] == "ms/token"
    assert payload["value"] > 0
    assert "small" in payload["rows"]
    row = payload["rows"]["small"]
    assert row["ms"] > 0 and row["x"] > 0
    # the profiler-derived I/T split rides each row (VERDICT r4 #8)
    assert "I" in row and "T" in row, row
    # the full table (the judge's artifact) carries every detailed field
    full = json.loads(full_path.read_text())
    frow = full["rows"]["small"]
    assert frow["value"] > 0 and frow["executed"] >= 1
    assert "startup_to_first_token_s" in frow
    assert frow["it_split"]["I_ms_per_token"] >= 0
    # drift defense (ISSUE 3): fingerprint + trial count ride every row
    fp = frow["env_fingerprint"]
    assert fp["jax"] and fp["backend"] == "cpu" and fp["clock"]
    assert frow["trials"] == 3  # default median-of-3, recorded


def test_compact_summary_shape_and_size():
    """_compact_summary: headline + per-row ms/x/I/T + [ms, x] scaling
    pairs; a full 9-row table must serialize far below the 2000-char
    driver capture that truncated round 4's record."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod2", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    it = {"I_ms_per_token": 8.123, "T_ms_per_token": 0.0, "basis": "x" * 200}
    rows = {"7b": {"value": 9.801, "vs_baseline": 50.4, "it_split": it,
                   "kv_cache": "f32", "samples": 64, "executed": 64},
            "13b": {"value": 17.9, "vs_baseline": 47.38, "it_split": it},
            "70b-tp8": {"value": 18.47, "vs_baseline": 262.2,
                        "shard_ms_measured": 16.05,
                        "ici_bandwidth_ms_modeled": 0.167,
                        "ici_latency_ms_modeled": 2.247,
                        "buffer_modes": {"f32": {"pad": "y" * 500}}}}
    for m in ("7b", "13b"):
        for n in (2, 4, 8):
            rows[f"{m}-tp{n}"] = {
                "value": 6.4, "vs_baseline": 124.0,
                "shard_ms_measured": 6.2,
                "ici_bandwidth_ms_modeled": 0.017,
                "ici_latency_ms_modeled": 0.129,
                "ici_latency_sensitivity_10x": {"f32_total_ms": 7.5}}
    configs = list(rows)
    curve = bench._scaling_curve(rows)
    out = bench._compact_summary(configs, rows, curve)
    line = json.dumps(out)
    assert len(line) < 1500, f"{len(line)} chars: {line[:200]}"
    assert out["value"] == 9.801 and out["vs_baseline"] == 50.4
    assert out["rows"]["7b"] == {"ms": 9.801, "x": 50.4, "I": 8.123,
                                 "T": 0.0}
    # tp rows: I = measured rank, T = modeled ICI total
    assert out["rows"]["70b-tp8"]["I"] == 16.05
    assert out["rows"]["70b-tp8"]["T"] == 2.414
    assert out["scaling_x_vs_same_n"]["7b"]["2"] == [6.4,
                                                     round(793.69 / 6.4, 2)]
    # failed rows surface as errors, never KeyError
    out2 = bench._compact_summary(
        ["7b", "13b"], {"7b": rows["7b"], "13b": {"error": "rc=1"}}, {})
    assert out2["rows"]["13b"] == {"error": "rc=1"}


def test_scaling_curve_assembly():
    """_scaling_curve (VERDICT r3 #2) mirrors the reference's per-device-
    count table: tp=1 from the measured single-chip row, tp>1 from the
    rank rows, same-n reference baselines, per-point kv_cache basis;
    missing/failed rows are skipped, empty rows give an empty curve."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    rows = {"7b": {"value": 9.8, "kv_cache": "f32"},
            "13b": {"value": 17.9, "kv_cache": "bf16"},
            "7b-tp2": {"value": 6.36, "kv_cache": "f32",
                       "shard_ms_measured": 6.22,
                       "ici_bandwidth_ms_modeled": 0.017,
                       "ici_latency_ms_modeled": 0.129},
            "13b-tp8": {"value": 6.6, "kv_cache": "f32",
                        "shard_ms_measured": 5.43,
                        "ici_bandwidth_ms_modeled": 0.047,
                        "ici_latency_ms_modeled": 1.127},
            "13b-tp4": {"error": "rc=1"},  # failed row: skipped
            "70b-tp8": {"value": 18.9}}    # not part of the curve
    curve = bench._scaling_curve(rows)
    assert set(curve) == {"7b", "13b"}
    assert curve["7b"]["1"]["reference_ms"] == 1312.50
    assert curve["7b"]["1"]["vs_reference_same_n"] == round(1312.50 / 9.8, 2)
    assert curve["7b"]["2"]["reference_ms"] == 793.69
    assert curve["7b"]["2"]["shard_ms_measured"] == 6.22
    # 13B has no published 1-device row; the measured point still appears
    assert curve["13b"]["1"]["reference_ms"] is None
    assert curve["13b"]["1"]["kv_cache"] == "bf16"
    assert curve["13b"]["8"]["vs_reference_same_n"] == round(1114.88 / 6.6, 2)
    assert "4" not in curve["13b"]  # failed row skipped
    assert bench._scaling_curve({}) == {}
    # _BASE scaling baselines derive from the same table (one source of
    # truth): spot-check through the public surface
    assert bench._REF_CURVE["13b"][4] == 848.19


def _load_bench(tag):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"bench_mod_{tag}", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_project_tp_reports_both_schemes(monkeypatch):
    """The tp-row projection carries BOTH schemes' modeled ICI (ref = the
    parity anchor), and the fused default's latency term is ~half the ref
    scheme's — the ISSUE 3 acceptance: 13b-tp8 projected total improves
    vs BENCH_r05's ref-scheme 7.419 ms/token record."""
    from distributed_llama_tpu.models.synth import llama2_13b_spec

    bench = _load_bench("proj")
    monkeypatch.delenv("DLLAMA_TP_SCHEME", raising=False)
    # BENCH_r05 13b-tp8: shard 6.245 measured, ref-scheme total 7.419
    out = bench._project_tp(llama2_13b_spec(), 8, 6.245, 848.19)
    assert out["tp_scheme"] == "fused"
    sch = out["schemes_f32"]
    assert set(sch) == {"ref", "fused", "overlap"}
    assert "parity anchor" in sch["ref"]["note"]
    L = llama2_13b_spec().n_layers
    assert sch["ref"]["n_collectives_per_token"] == 4 * L + 1
    assert sch["fused"]["n_collectives_per_token"] == 2 * L + 1
    assert sch["fused"]["ici_latency_ms_modeled"] < \
        sch["ref"]["ici_latency_ms_modeled"] * 0.55
    # the headline (active scheme) total beats the recorded ref total
    assert out["value"] == sch["fused"]["total_ms"] < 7.419
    assert sch["ref"]["total_ms"] == 7.419  # the BENCH_r05 anchor
    # the overlap row (ISSUE 10): 2L(S-1) ppermutes + 2L+1 gathers, with
    # the hidden term carried and subtracted — modeled strictly faster
    # than fused at 13b-tp8 (the acceptance criterion)
    assert sch["overlap"]["n_collectives_per_token"] == \
        2 * L * 7 + 2 * L + 1
    assert sch["overlap"]["ici_hidden_ms_modeled"] > 0
    assert sch["overlap"]["total_ms"] < sch["fused"]["total_ms"]

    # under DLLAMA_TP_SCHEME=ref the headline IS the anchor row
    monkeypatch.setenv("DLLAMA_TP_SCHEME", "ref")
    out_ref = bench._project_tp(llama2_13b_spec(), 8, 6.245, 848.19)
    assert out_ref["tp_scheme"] == "ref"
    assert out_ref["value"] == 7.419


def test_bench_trials_env(monkeypatch):
    bench = _load_bench("trials")
    monkeypatch.delenv("DLLAMA_BENCH_TRIALS", raising=False)
    assert bench._bench_trials() == 3
    monkeypatch.setenv("DLLAMA_BENCH_TRIALS", "7")
    assert bench._bench_trials() == 7
    monkeypatch.setenv("DLLAMA_BENCH_TRIALS", "0")
    import pytest

    with pytest.raises(SystemExit):
        bench._bench_trials()


def test_row_env_policy():
    """The per-row kernel-policy envs are A/B-backed (BASELINE.md r5) and
    must never override explicit user env."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_mod3", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    assert bench._row_env("13b-tp2", {})["DLLAMA_Q40_I4"] == "on"
    assert bench._row_env("13b-tp4", {})["DLLAMA_Q40_I4"] == "on"
    assert "DLLAMA_Q40_I4" not in bench._row_env("13b-tp8", {})
    assert "DLLAMA_Q40_I4" not in bench._row_env("13b", {})
    e7 = bench._row_env("7b", {})
    assert e7 == {"DLLAMA_Q40_I4": "on", "DLLAMA_NB_MAJOR": "force"}
    for cfg in ("7b-tp2", "7b-tp4", "7b-tp8", "70b-tp8"):
        assert bench._row_env(cfg, {}) == {}
    # explicit user env always wins
    assert bench._row_env("7b", {"DLLAMA_Q40_I4": "off"}) == \
        {"DLLAMA_Q40_I4": "off"}
    assert bench._row_env("13b-tp2", {"DLLAMA_Q40_I4": "off"}) == \
        {"DLLAMA_Q40_I4": "off"}
