"""racecheck (ISSUE 17): the deterministic interleaving harness itself.
Same seed => same schedule set; every seam's clean run is green with the
allocator-audit/ledger-conservation oracles; both seeded mutations break
exactly the invariant they target and drive the exit code to 1."""

from __future__ import annotations

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

import racecheck  # noqa: E402


# -- schedule generation ---------------------------------------------------


def test_exhaustive_enumeration_matches_the_multinomial():
    counts = (3, 2)
    scheds = list(racecheck.exhaustive_schedules(counts))
    assert len(scheds) == racecheck.n_interleavings(counts) == 10
    assert len(set(scheds)) == 10
    for s in scheds:
        assert s.count(0) == 3 and s.count(1) == 2


def test_sampled_schedules_are_seed_deterministic_and_distinct():
    a = racecheck.sampled_schedules((3, 3, 3), target=100, seed=7)
    b = racecheck.sampled_schedules((3, 3, 3), target=100, seed=7)
    c = racecheck.sampled_schedules((3, 3, 3), target=100, seed=8)
    assert a == b
    assert len(set(a)) == 100
    assert a != c  # a different seed explores a different set
    assert racecheck.schedule_digest(a) == racecheck.schedule_digest(b)
    assert racecheck.schedule_digest(a) != racecheck.schedule_digest(c)


def test_run_digest_is_reproducible_across_invocations():
    r1 = racecheck.run(seed=3, seams=["ledger_drain"])
    r2 = racecheck.run(seed=3, seams=["ledger_drain"])
    assert (r1["seams"]["ledger_drain"]["digest"]
            == r2["seams"]["ledger_drain"]["digest"])


# -- clean runs ------------------------------------------------------------


def test_pure_host_seams_run_clean_at_full_depth():
    row = racecheck.run(seed=0, seams=["pool_adopt", "upload_settle",
                                       "ledger_drain"])
    assert row["ok"], row
    for name, r in row["seams"].items():
        assert r["failures"] == 0, (name, r)
        assert r["explored"] >= 100, (name, r)


def test_engine_seam_runs_clean():
    row = racecheck.run(seed=0, seams=["ingest_sweep"])
    r = row["seams"]["ingest_sweep"]
    assert row["ok"], r
    assert r["mode"] == "exhaustive" and r["explored"] >= 100


# -- the seeded mutations (the gate's self-test) ---------------------------


def test_drop_a_lock_breaks_the_allocator_audit():
    row = racecheck.run(seed=0, seams=["pool_adopt"],
                        inject="drop-a-lock")
    r = row["seams"]["pool_adopt"]
    assert not row["ok"]
    assert r["failures"] > 0
    blob = " ".join(p for f in r["first_failures"]
                    for p in f["problems"])
    # the torn alloc manifests as pool-accounting damage: either the
    # audit's refcount mismatch or the double-claim's release explosion
    assert "page" in blob, blob


def test_reorder_inbox_breaks_fifo_admission():
    row = racecheck.run(seed=0, seams=["ingest_sweep"],
                        inject="reorder-inbox")
    r = row["seams"]["ingest_sweep"]
    assert not row["ok"]
    assert r["failures"] > 0
    blob = " ".join(p for f in r["first_failures"]
                    for p in f["problems"])
    assert "FIFO" in blob, blob


# -- CLI contract ----------------------------------------------------------


def test_cli_exit_codes_are_exact(capsys):
    assert racecheck.main(["--seam", "ledger_drain"]) == 0
    assert racecheck.main(["--seam", "pool_adopt",
                           "--inject", "drop-a-lock"]) == 1
    assert racecheck.main(["--target", "0"]) == 2
    capsys.readouterr()


def test_cli_emits_one_json_row(capsys):
    import json

    rc = racecheck.main(["--seam", "ledger_drain", "--seed", "5"])
    out = capsys.readouterr().out.strip().splitlines()
    row = json.loads(out[-1])
    assert rc == 0
    assert row["kind"] == "racecheck" and row["seed"] == 5
    assert row["seams"]["ledger_drain"]["explored"] >= 100


def test_mutations_leave_clean_seams_clean():
    # drop-a-lock rearms only pool_adopt's alloc ops: the ledger seam
    # under the same flag must stay green (the mutation is targeted,
    # not a harness-wide poison)
    row = racecheck.run(seed=0, seams=["ledger_drain"],
                        inject="drop-a-lock")
    assert row["ok"], row


@pytest.mark.slow
def test_full_default_run_is_green():
    row = racecheck.run(seed=0)
    assert row["ok"], {n: r["failures"]
                       for n, r in row["seams"].items()}
    assert set(row["seams"]) == set(racecheck.SEAM_NAMES)
    for name, r in row["seams"].items():
        assert r["explored"] >= 100, (name, r)
