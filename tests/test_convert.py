"""Converter tests: Meta shard re-concatenation and HF end-to-end parity.

The HF test is the strongest numerics gate in the suite: a random tiny
LlamaForCausalLM is converted to the reference .bin format and OUR forward
must reproduce the transformers forward's logits (f32) — covering the RoPE
un-permutation, tensor ordering, GQA mapping, SwiGLU and norms in one shot.
"""

import json
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from distributed_llama_tpu.convert import convert_hf, convert_meta
from distributed_llama_tpu.io.loader import load_model
from distributed_llama_tpu.ops.quants import FloatType, dequantize_q40


def _meta_dir(tmp_path, n_shards=2):
    """Fake Meta checkpoint: dim 64, 2 layers, 4 heads, TP-sharded tensors."""
    dim, hidden, n_layers, n_heads, vocab = 64, 96, 2, 4, 128
    rng = np.random.default_rng(0)
    full = {}

    def t(*shape):
        return (rng.standard_normal(shape) * 0.1).astype(np.float32)

    full["tok_embeddings.weight"] = t(vocab, dim)
    full["norm.weight"] = 1 + t(dim)
    full["output.weight"] = t(vocab, dim)
    for i in range(n_layers):
        full[f"layers.{i}.attention_norm.weight"] = 1 + t(dim)
        full[f"layers.{i}.ffn_norm.weight"] = 1 + t(dim)
        for k in ("wq", "wk", "wv", "wo"):
            full[f"layers.{i}.attention.{k}.weight"] = t(dim, dim)
        full[f"layers.{i}.feed_forward.w1.weight"] = t(hidden, dim)
        full[f"layers.{i}.feed_forward.w2.weight"] = t(dim, hidden)
        full[f"layers.{i}.feed_forward.w3.weight"] = t(hidden, dim)

    # shard like Meta TP: dim=1 for tok_embeddings/wo/w2, dim=0 otherwise,
    # 1-D tensors replicated (converter.py:131-148)
    axis1 = {"tok_embeddings.weight"} | {
        f"layers.{i}.attention.wo.weight" for i in range(n_layers)} | {
        f"layers.{i}.feed_forward.w2.weight" for i in range(n_layers)}
    shards = [{} for _ in range(n_shards)]
    for key, arr in full.items():
        if arr.ndim == 1:
            for s in shards:
                s[key] = torch.from_numpy(arr.copy())
        else:
            ax = 1 if key in axis1 else 0
            for s, part in zip(shards, np.array_split(arr, n_shards, axis=ax)):
                s[key] = torch.from_numpy(np.ascontiguousarray(part))
    d = tmp_path / "meta"
    d.mkdir()
    for i, s in enumerate(shards):
        torch.save(s, str(d / f"consolidated.{i:02d}.pth"))
    # vocab_size=-1 sentinel: the converter must derive it from the embedding
    (d / "params.json").write_text(json.dumps(
        {"dim": dim, "n_layers": n_layers, "n_heads": n_heads,
         "vocab_size": -1, "norm_eps": 1e-5}))
    return str(d), full


def test_convert_meta_reconcatenates_shards(tmp_path):
    path, full = _meta_dir(tmp_path)
    out = str(tmp_path / "m.bin")
    convert_meta(path, "q40", out=out, seq_len=32)
    spec, params = load_model(out, weights_float_type=FloatType.Q40)
    assert spec.vocab_size == 128  # derived despite the -1 sentinel
    assert spec.hidden_dim == 96

    # embeddings/norms are exact f32; matmuls round-trip through Q40
    np.testing.assert_array_equal(params["tok_embedding"],
                                  full["tok_embeddings.weight"])
    np.testing.assert_array_equal(params["rms_final"], full["norm.weight"])
    got_w1 = dequantize_q40(np.asarray(params["w1"].qs[1]),
                            np.asarray(params["w1"].d16[1]))
    want = full["layers.1.feed_forward.w1.weight"]
    # Q40 rounding only: per-block delta ~ max|x|/8 ~ 0.05 at this scale
    assert np.abs(got_w1 - want).max() < 0.06


def test_convert_hf_logit_parity(tmp_path):
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(hidden_size=64, intermediate_size=96,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, vocab_size=128,
                      max_position_embeddings=64, rms_norm_eps=1e-5,
                      tie_word_embeddings=False)
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg).eval()
    hf_dir = str(tmp_path / "hf")
    model.save_pretrained(hf_dir)

    out = str(tmp_path / "hf.bin")
    convert_hf(hf_dir, "float32", out=out, seq_len=32)
    spec, params = load_model(out, weights_float_type=FloatType.F32)
    assert spec.n_kv_heads == 2  # GQA carried through

    tokens = np.array([5, 17, 99, 3], dtype=np.int64)
    with torch.no_grad():
        want = model(torch.from_numpy(tokens)[None]).logits[0].numpy()

    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)

    got, _ = forward(spec, params_to_device(params), init_cache(spec),
                     jnp.asarray(tokens, jnp.int32), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-3, atol=1e-3)
