"""Single-dispatch mixed prefill+decode batches (ISSUE 18).

Four layers of gates, mirroring test_speculative.py's:

* device parity: the span-gated mixed forward is the SAME program as the
  K-query verify forward (bitwise logits on identical inputs — span only
  gates cache writes), all-span-1 windows reproduce the plain decode
  step bitwise, and positions past a row's span dead-write to the scrap
  page — never onto live pages;
* engine behavior: token streams under ``dispatch_tokens`` are bitwise
  the separate-dispatch engine's — greedy AND seeded-sampled, f32 and q8
  KV, across budget edges (budget smaller than one decode round forcing
  budget_wait deferral, budget larger than any remaining prompt,
  prefill-only tails) and over the tp mesh for all three collective
  schemes;
* accounting: kind="mixed" census rows satisfy the exact ledger/census
  conservation equalities, deferred rows bill budget_wait stalls, and
  healthy runs carry zero overrun steps (the chaos overrun mutation
  makes them non-zero without corrupting streams);
* analytic lockstep: shard_sim's MixedProjection composes from the
  projection's own components, memory_model prices the budget window
  with the verify-window formula (one t_len, two knobs), and the
  spec_k/dispatch_tokens mutual exclusion holds at every layer.

The bitwise claims lean on the same property the verify keystone pinned:
jitted XLA per-row logits are bitwise stable across t_len changes AT
FIXED BATCH, and engine dispatches always carry B=slots.
"""

import functools

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=32)
# tp=4 needs n_kv_heads % 4 == 0
SPEC_TP4 = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                           n_kv_heads=4, vocab_size=128, seq_len=32)


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


# -- device parity ----------------------------------------------------------


@pytest.mark.parametrize("wtype", ["f32", "q40"])
def test_mixed_full_span_is_bitwise_the_verify_forward(wtype):
    """With every row's span = T the write gate is inactive, so the mixed
    forward must be the verify forward EXACTLY — bitwise logits AND cache
    on scrambled physical pages. Any divergence means the span plumbing
    changed the program, not just the writes."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward_batch_mixed_paged,
                                                    forward_batch_spec_paged,
                                                    init_cache_paged,
                                                    params_to_device)

    tree = synth_params(SPEC, q40=(wtype == "q40"), seed=4, scale=0.3)
    params_dev = params_to_device(tree)
    ps, B, T = 4, 2, 3
    max_pages = SPEC.seq_len // ps
    cache_a = init_cache_paged(SPEC, B * max_pages + 1, ps)
    cache_b = init_cache_paged(SPEC, B * max_pages + 1, ps)
    table = np.zeros((B, max_pages), np.int32)
    for b in range(B):
        table[b] = 1 + np.arange(max_pages) * B + b
    verify = jax.jit(functools.partial(forward_batch_spec_paged, SPEC, ps),
                     donate_argnums=1)
    mixed = jax.jit(functools.partial(forward_batch_mixed_paged, SPEC, ps),
                    donate_argnums=1)
    rng = np.random.default_rng(7)
    pos = np.array([0, 5], np.int32)
    toks = rng.integers(2, 100, (B, T)).astype(np.int32)
    vg, cache_a = verify(params_dev, cache_a, jnp.asarray(toks),
                         jnp.asarray(pos), jnp.asarray(table))
    mg, cache_b = mixed(params_dev, cache_b, jnp.asarray(toks),
                        jnp.asarray(pos),
                        jnp.asarray(np.full(B, T, np.int32)),
                        jnp.asarray(table))
    np.testing.assert_array_equal(np.asarray(vg), np.asarray(mg))
    np.testing.assert_array_equal(np.asarray(cache_a.k),
                                  np.asarray(cache_b.k))
    np.testing.assert_array_equal(np.asarray(cache_a.v),
                                  np.asarray(cache_b.v))


def test_mixed_all_span_one_reproduces_plain_decode(params):
    """A T-wide window where every row has span 1 (padding in cols 1+)
    must emit col-0 logits bitwise equal to the plain 1-token decode step
    at the same batch, and leave the cache equal except the scrap page
    (where the padded columns dead-write)."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward_batch_mixed_paged,
                                                    forward_batch_paged,
                                                    init_cache_paged,
                                                    params_to_device)
    from distributed_llama_tpu.runtime.paging import SCRAP_PAGE

    params_dev = params_to_device(params)
    ps, B, T = 4, 2, 3
    max_pages = SPEC.seq_len // ps
    cache_a = init_cache_paged(SPEC, B * max_pages + 1, ps)
    cache_b = init_cache_paged(SPEC, B * max_pages + 1, ps)
    table = np.zeros((B, max_pages), np.int32)
    for b in range(B):
        table[b] = 1 + np.arange(max_pages) * B + b
    step = jax.jit(functools.partial(forward_batch_paged, SPEC, ps),
                   donate_argnums=1)
    mixed = jax.jit(functools.partial(forward_batch_mixed_paged, SPEC, ps),
                    donate_argnums=1)
    pos = np.array([0, 0], np.int32)
    toks = np.array([7, 9], np.int32)
    lg, cache_a = step(params_dev, cache_a, jnp.asarray(toks),
                       jnp.asarray(pos), jnp.asarray(table))
    win = np.zeros((B, T), np.int32)
    win[:, 0] = toks
    mg, cache_b = mixed(params_dev, cache_b, jnp.asarray(win),
                        jnp.asarray(pos),
                        jnp.asarray(np.ones(B, np.int32)),
                        jnp.asarray(table))
    np.testing.assert_array_equal(np.asarray(lg), np.asarray(mg)[:, 0])
    ka, kb = np.asarray(cache_a.k), np.asarray(cache_b.k)
    live = [p for p in range(ka.shape[1]) if p != SCRAP_PAGE]
    np.testing.assert_array_equal(ka[:, live], kb[:, live])


def test_mixed_span_edge_writes_route_to_scrap(params):
    """Positions past a row's span must dead-write onto the scrap page:
    compare against the ungated verify forward on identical inputs — the
    two caches may differ ONLY on the scrap page and the pages holding
    the gated row's beyond-span positions (changed by verify, untouched
    by mixed)."""
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward_batch_mixed_paged,
                                                    forward_batch_spec_paged,
                                                    init_cache_paged,
                                                    params_to_device)
    from distributed_llama_tpu.runtime.paging import SCRAP_PAGE

    params_dev = params_to_device(params)
    ps, B, T = 4, 2, 3
    max_pages = SPEC.seq_len // ps
    cache_a = init_cache_paged(SPEC, B * max_pages + 1, ps)
    cache_b = init_cache_paged(SPEC, B * max_pages + 1, ps)
    table = np.zeros((B, max_pages), np.int32)
    for b in range(B):
        table[b] = 1 + np.arange(max_pages) * B + b
    verify = jax.jit(functools.partial(forward_batch_spec_paged, SPEC, ps),
                     donate_argnums=1)
    mixed = jax.jit(functools.partial(forward_batch_mixed_paged, SPEC, ps),
                    donate_argnums=1)
    rng = np.random.default_rng(3)
    pos = np.array([0, 0], np.int32)
    toks = rng.integers(2, 100, (B, T)).astype(np.int32)
    span = np.array([1, T], np.int32)  # row 0 gated after col 0
    vg, cache_a = verify(params_dev, cache_a, jnp.asarray(toks),
                         jnp.asarray(pos), jnp.asarray(table))
    mg, cache_b = mixed(params_dev, cache_b, jnp.asarray(toks),
                        jnp.asarray(pos), jnp.asarray(span),
                        jnp.asarray(table))
    # within-span logits are bitwise the ungated forward's; beyond-span
    # columns read back their own scrap-routed writes, so they compute
    # different junk — junk the engine discards host-side either way
    vg, mg = np.asarray(vg), np.asarray(mg)
    np.testing.assert_array_equal(vg[0, :1], mg[0, :1])
    np.testing.assert_array_equal(vg[1], mg[1])
    ka, kb = np.asarray(cache_a.k), np.asarray(cache_b.k)
    diff_pages = {int(p) for _, p in
                  np.argwhere((ka != kb).any(axis=(2, 3, 4)))}
    # row 0's beyond-span positions 1..2 live on its page 0 (pos < 4):
    # verify wrote them, mixed routed them to scrap
    assert diff_pages <= {SCRAP_PAGE, int(table[0, 0])}
    # row 1 (full span) is bitwise identical everywhere
    np.testing.assert_array_equal(ka[:, table[1]], kb[:, table[1]])


# -- engine behavior: stream parity across budget edges ---------------------


def _reqs(n=6, seed=11):
    rng = np.random.default_rng(seed)
    return [[1] + list(rng.integers(3, 120, rng.integers(2, 14)))
            for _ in range(n)]


def _run(tree, reqs, steps, spec=SPEC, **kw):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    eng = ContinuousEngine(spec, tree, slots=kw.pop("slots", 4),
                           temperature=kw.pop("temperature", 0.0),
                           topp=0.9, seed=7, page_size=4,
                           kv_pages=kw.pop("kv_pages", 32), **kw)
    outs, stats = eng.run([list(r) for r in reqs], steps)
    return eng, outs, stats


_REF_CACHE = {}


def _ref_stream(params, temperature):
    # the separate-dispatch reference only depends on temperature —
    # cache it across the budget parametrization (each engine build
    # recompiles its jitted closures; this is the suite's cost center)
    if temperature not in _REF_CACHE:
        _, ref, _ = _run(params, _reqs(), 24, temperature=temperature,
                         prefill_chunk=4)
        _REF_CACHE[temperature] = ref
    return _REF_CACHE[temperature]


@pytest.mark.parametrize("budget,temperature",
                         [(2, 0.0), (2, 0.9), (4, 0.0), (4, 0.9),
                          (8, 0.0), (8, 0.9), (16, 0.9)])
def test_mixed_streams_bitwise_equal_separate_dispatch(params, budget,
                                                       temperature):
    """ISSUE 18 acceptance: token streams under every budget — including
    budget=2 < slots (decode rounds split across dispatches via
    budget_wait deferral) and budget=16 > any remaining prompt (whole
    prompts land in one slice; seeded-sampled, the stronger claim) —
    are bitwise the separate-dispatch engine's, greedy AND
    seeded-sampled."""
    ref = _ref_stream(params, temperature)
    eng, got, st = _run(params, _reqs(), 24, temperature=temperature,
                        dispatch_tokens=budget)
    assert got == ref
    assert st.overrun_steps == 0
    assert all(s.free for s in eng._pool)


def test_mixed_streams_bitwise_equal_q8_kv(params):
    """Quantized KV: the q8 paged mixed attend path (span-gated
    paged_attention_q8) keeps streams bitwise the q8 separate-dispatch
    engine's.  Pinned on this workload like test_kv_quant's claims — q8
    amplifies XLA:CPU program-shape noise across code boundaries, so the
    reference is the PLAIN q8 engine (no chunked prefill: chunking
    changes the prefill program shape, which under q8 can flip a token
    on long random prompts; that divergence is the quantizer's, not the
    scheduler's)."""
    _, ref, _ = _run(params, _reqs(4), 16, slots=3, kv_pages=24,
                     kv_quant="q8")
    _, got, st = _run(params, _reqs(4), 16, slots=3, kv_pages=24,
                      dispatch_tokens=6, kv_quant="q8")
    assert got == ref
    assert st.overrun_steps == 0


def test_mixed_prefill_only_tail_and_zero_active_decodes(params):
    """One long prompt, zero other work: every dispatch is slice-only
    (no active decode rows) until prefill completes — the budget path
    must handle the degenerate fill and still match the reference."""
    long_prompt = [[1] + [5 + (i % 20) for i in range(20)]]
    _, ref, _ = _run(params, long_prompt, 25, prefill_chunk=4)
    _, got, st = _run(params, long_prompt, 25, dispatch_tokens=8)
    assert got == ref
    # 20 forced prompt positions ride 3 budget-8 dispatches (the sole
    # row's slice fills the whole window), then the sampled tail decodes
    # one token per dispatch
    assert st.steps >= 5
    assert st.tokens == 25


@pytest.mark.parametrize("scheme", ["ref", "fused", "overlap"])
def test_mixed_streams_bitwise_over_tp_mesh(scheme, monkeypatch):
    """All three tp collective schemes: the sharded mixed dispatch
    (tp.make_sharded_mixed) keeps greedy streams bitwise equal to the
    single-chip separate-dispatch engine."""
    from distributed_llama_tpu.parallel import make_mesh

    tree = synth_params(SPEC, q40=False, seed=4, scale=0.3)
    reqs = _reqs(4)
    _, ref, _ = _run(tree, reqs, 16, slots=3, kv_pages=24,
                     prefill_chunk=4)
    monkeypatch.setenv("DLLAMA_TP_SCHEME", scheme)
    _, got, st = _run(tree, reqs, 16, slots=3, kv_pages=24,
                      dispatch_tokens=6, mesh=make_mesh(tp=2))
    assert got == ref
    assert st.overrun_steps == 0


def test_mixed_streams_bitwise_tp4(monkeypatch):
    """tp=4 (needs n_kv_heads % 4 == 0): the wider mesh keeps mixed
    streams bitwise the single-chip reference."""
    from distributed_llama_tpu.parallel import make_mesh

    tree = synth_params(SPEC_TP4, q40=False, seed=4, scale=0.3)
    reqs = _reqs(4)
    _, ref, _ = _run(tree, reqs, 12, spec=SPEC_TP4, slots=3, kv_pages=24,
                     prefill_chunk=4)
    monkeypatch.setenv("DLLAMA_TP_SCHEME", "fused")
    _, got, _ = _run(tree, reqs, 12, spec=SPEC_TP4, slots=3, kv_pages=24,
                     dispatch_tokens=6, mesh=make_mesh(tp=4))
    assert got == ref


def test_mixed_sp_is_rejected_loudly():
    """Sequence-parallel meshes have no mixed program — the pairing must
    raise at build time, not silently fall back."""
    from distributed_llama_tpu.models.synth import small_bench_spec
    from distributed_llama_tpu.ops.quants import FloatType
    from distributed_llama_tpu.parallel import make_mesh, make_sharded_mixed

    spec = small_bench_spec(weights_float_type=FloatType.F32)
    with pytest.raises(ValueError, match="sp=1"):
        make_sharded_mixed(spec, make_mesh(tp=2, sp=2), 16)


# -- engine validation ------------------------------------------------------


def test_dispatch_tokens_incompatible_with_spec_k(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    with pytest.raises(ValueError, match="spec_k"):
        ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                         seed=7, page_size=4, kv_pages=16, spec_k=4,
                         dispatch_tokens=8)


def test_dispatch_tokens_requires_paged_cache(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    with pytest.raises(ValueError, match="page"):
        ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                         seed=7, dispatch_tokens=8)


def test_dispatch_tokens_auto_and_floor(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    eng = ContinuousEngine(SPEC, params, slots=4, temperature=0.0,
                           topp=0.9, seed=7, page_size=4, kv_pages=32,
                           prefill_chunk=8, dispatch_tokens=-1)
    # -1 auto-sizes from the chunk knob: slots-1 decode rows + a chunk
    assert eng.dispatch_tokens == 4 - 1 + 8
    with pytest.raises(ValueError, match="dispatch_tokens"):
        ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                         seed=7, page_size=4, kv_pages=16,
                         dispatch_tokens=1)


# -- accounting: conservation, budget_wait, overrun chaos -------------------


def test_mixed_census_and_ledger_conserve(params):
    """The exact equalities on a mixed engine: census rows are
    kind="mixed", row-steps match stats.sum_active AND the summed ledger
    bills, tokens match, no ledgers stay open. Budget 2 < slots forces
    deferrals, so budget_wait stalls must appear on BOTH books."""
    eng, _, st = _run(params, _reqs(), 20, dispatch_tokens=2)
    totals = eng.sched_census.totals()
    kinds = {e["kind"] for e in eng.sched_census.tail(10_000)}
    assert kinds == {"mixed"}
    grand = eng.ledger_book.grand_totals()
    assert totals["row_steps"] == st.sum_active
    assert grand["decode_row_steps"] == st.sum_active
    assert totals["steps"] == st.steps
    assert sum(totals["tokens"].values()) == st.tokens
    assert eng.ledger_book.n_open == 0
    assert grand["stall_steps"].get("budget_wait", 0) > 0
    census_stalls = sum(e.get("parked", {}).get("budget_wait", 0)
                        for e in eng.sched_census.tail(10_000))
    assert census_stalls > 0


def test_mixed_overrun_chaos_counts_but_streams_survive(params):
    """The overrun mutation packs slices past the budget: overrun_steps
    must go non-zero (the loadcheck gate's hook) while streams stay
    bitwise correct — the mutation wastes budget, it does not corrupt."""
    from distributed_llama_tpu.runtime.chaos import ChaosMonkey

    ref = _ref_stream(params, 0.0)
    chaos = ChaosMonkey(overrun_budget=True)
    _, got, st = _run(params, _reqs(), 24, dispatch_tokens=4, chaos=chaos)
    assert got == ref
    assert st.overrun_steps > 0
    assert chaos.overran_budgets > 0
    assert chaos.injection_summary()["overran_budgets"] > 0


def test_chaos_parse_overrun_budget():
    from distributed_llama_tpu.runtime.chaos import ChaosMonkey

    assert ChaosMonkey.parse("overrun_budget=1").overrun_budget
    assert not ChaosMonkey.parse("overrun_budget=0").overrun_budget


# -- satellite 1: chunk charging is pinned at dispatch ----------------------


def test_prefill_chunk_charge_survives_preemption_resume(params):
    """The suspected double-charge (a chunk billed at park AND again at
    resume) does NOT exist: prefill_chunks increments inside the
    per-window forward closure, at DISPATCH. Pin it — under a hold that
    parks the prefill at its first chunk boundary and resumes next
    iteration, every dispatched window has a UNIQUE start offset and the
    counter equals the dispatch count exactly."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=7, prefill_chunk=4, page_size=4,
                           kv_pages=16, prefix_share=False)
    calls = []
    real_fwd = eng._prefill_fwd

    def counting_fwd(params_, cache, part, start):
        calls.append(int(start))
        return real_fwd(params_, cache, part, start)

    eng._prefill_fwd = counting_fwd
    fired = []

    def hold_once(slot):
        fired.append(slot)
        return len(fired) == 1  # park at the first boundary, then resume

    eng.prefill_hold = hold_once
    # steps must exceed the prompt replay (s.budget = min(steps, seq_len)
    # gates chunked prefill on n_pre < budget)
    eng.submit(Request(tokens=[1] + [5 + (i % 20) for i in range(13)],
                       steps=24))
    while eng.step_once(quiet=True):
        pass
    assert fired  # the hold actually interposed
    assert len(calls) == len(set(calls))  # no window dispatched twice
    assert eng.stats.prefill_chunks == len(calls)


# -- analytic lockstep ------------------------------------------------------


def test_shard_sim_mixed_composes_from_projection_components():
    from distributed_llama_tpu.models.synth import small_bench_spec
    from distributed_llama_tpu.ops.quants import FloatType
    from distributed_llama_tpu.parallel.shard_sim import project_full_system

    spec = small_bench_spec(weights_float_type=FloatType.F32)
    proj = project_full_system(spec, 4, 10.0, scheme="fused")
    m = proj.mixed(16)
    want = (proj.shard_ms + 16 * proj.ici_bandwidth_ms
            + proj.ici_latency_ms - proj.ici_hidden_ms)
    assert m.dispatch_ms == round(want, 3)
    assert m.slice_tokens == 15
    # the piggybacked slice must be cheaper per token than a separate
    # chunk dispatch — that delta IS the feature's claim
    assert m.prefill_speedup > 1.0
    assert m.baseline_ms_per_token == round(proj.total_ms, 3)
    with pytest.raises(ValueError, match="budget"):
        proj.mixed(1)


def test_memory_model_mixed_budget_is_the_verify_width():
    """One t_len formula, two knobs: pricing mixed_budget=K must equal
    pricing spec_k=K bitwise, and pricing both at once must raise (the
    engine rejects the pairing)."""
    from distributed_llama_tpu.analysis.memory_model import device_footprint
    from distributed_llama_tpu.models.synth import small_bench_spec
    from distributed_llama_tpu.ops.quants import FloatType

    spec = small_bench_spec(weights_float_type=FloatType.F32)
    a = device_footprint(spec, 4, "fused", kv_page_size=16,
                         mixed_budget=8)
    b = device_footprint(spec, 4, "fused", kv_page_size=16, spec_k=8)
    assert a.total_bytes == b.total_bytes
    plain = device_footprint(spec, 4, "fused", kv_page_size=16)
    assert a.total_bytes > plain.total_bytes  # the window costs something
    with pytest.raises(ValueError, match="mutually exclusive"):
        device_footprint(spec, 4, "fused", kv_page_size=16, spec_k=4,
                         mixed_budget=8)


def test_comm_stats_budget_scaling_is_the_mixed_contract_model():
    """The analytic half the J001 mixed census pins: byte budget at
    t_len=T is exactly T x the per-token budget, counts unchanged."""
    from distributed_llama_tpu.models.synth import small_bench_spec
    from distributed_llama_tpu.ops.quants import FloatType
    from distributed_llama_tpu.parallel.comm_stats import tp_collective_budget

    spec = small_bench_spec(weights_float_type=FloatType.F32)
    for scheme in ("ref", "fused", "overlap"):
        one = tp_collective_budget(spec, 4, scheme)
        many = tp_collective_budget(spec, 4, scheme, t_len=12)
        assert many.kind_counts() == one.kind_counts()
        assert many.moved_bytes == 12 * one.moved_bytes
