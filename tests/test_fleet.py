"""Fleet signal plane (ISSUE 15): /health+/metrics parsing, rollup math,
the deterministic multi-replica sim."""

import argparse
import json
import os
import sys
import urllib.request

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

from distributed_llama_tpu.models.spec import TransformerSpec  # noqa: E402
from distributed_llama_tpu.models.synth import synth_params  # noqa: E402
from distributed_llama_tpu.obs.fleet import (ReplicaSignals,  # noqa: E402
                                             apply_metrics, parse_metrics,
                                             rollup, scrape_replica,
                                             signals_from_health)

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


class _IdTokenizer:
    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"<%d>" % tok


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


def _row(name, **kw):
    defaults = dict(slots=4, active=2, queue_depth=1, kv_pages=20,
                    kv_pages_free=8, prefix_hits=4, prefix_misses=4,
                    generated_tokens=100, steps=50,
                    prefill_tokens_saved=16, goodput_tokens=60,
                    slo={"interactive": {"attempted": 10, "met": 8,
                                         "violated": 2, "failed": 0,
                                         "goodput_tokens": 60}})
    defaults.update(kw)
    return ReplicaSignals(name=name, **defaults)


def test_rollup_sums_counts_and_recomputes_ratios():
    rows = [_row("a"),
            _row("b", active=4, kv_pages_free=0, prefix_hits=12,
                 prefix_misses=0, goodput_tokens=0,
                 slo={"interactive": {"attempted": 10, "met": 2,
                                      "violated": 8, "failed": 0,
                                      "goodput_tokens": 0},
                      "batch": {"attempted": 5, "met": 5, "violated": 0,
                                "failed": 0, "goodput_tokens": 40}})]
    agg = rollup(rows)
    assert agg.replicas == 2 and agg.healthy == 2
    assert agg.slots == 8 and agg.active == 6
    assert agg.kv_pages_free == 8 and agg.queue_depth == 2
    # fleet hit rate from SUMMED counts: (4+12)/(4+12+4+0)
    assert agg.prefix_hit_rate == pytest.approx(16 / 20)
    # fleet attainment from SUMMED counts, not averaged ratios:
    # interactive (8+2)/(10+10) = 0.5 — the mean of ratios (0.8, 0.2)
    # happens to agree here, so pin a case where it would not
    assert agg.attainment["interactive"] == pytest.approx(0.5)
    assert agg.attainment["batch"] == 1.0
    assert agg.goodput_tokens == 60
    j = agg.to_json()
    assert j["attainment"]["interactive"] == 0.5


def test_rollup_unhealthy_rows_do_not_dilute():
    rows = [_row("up"), ReplicaSignals(name="down", healthy=False,
                                       error="ConnectionRefusedError")]
    agg = rollup(rows)
    assert agg.replicas == 2 and agg.healthy == 1
    assert agg.slots == 4  # the dead box contributed nothing
    assert agg.occupancy == pytest.approx(0.5)


def test_rollup_attainment_weighs_by_attempts_not_replicas():
    """The averaging trap the module refuses: a drained replica at 1.0
    must not launder a loaded replica's misses."""
    idle = _row("idle", slo={"interactive": {
        "attempted": 1, "met": 1, "violated": 0, "failed": 0,
        "goodput_tokens": 5}})
    loaded = _row("loaded", slo={"interactive": {
        "attempted": 99, "met": 0, "violated": 99, "failed": 0,
        "goodput_tokens": 0}})
    agg = rollup([idle, loaded])
    assert agg.attainment["interactive"] == pytest.approx(1 / 100)
    # the mean-of-ratios answer would have been 0.505 — visibly wrong


def test_parse_metrics_and_apply():
    text = ("# HELP dllama_prefix_hits_total x\n"
            "# TYPE dllama_prefix_hits_total counter\n"
            "dllama_prefix_hits_total 7\n"
            "dllama_kv_pages_free 3\n"
            "dllama_queue_depth 2\n"
            'dllama_goodput_tokens_total{class="interactive"} 11\n'
            'dllama_goodput_tokens_total{class="batch"} 4\n')
    samples = parse_metrics(text)
    assert samples["dllama_prefix_hits_total"] == 7.0
    row = apply_metrics(ReplicaSignals(name="r"), samples)
    assert row.prefix_hits == 7
    assert row.kv_pages_free == 3
    assert row.queue_depth == 2
    assert row.goodput_tokens == 15
    with pytest.raises(ValueError):
        parse_metrics("dllama_bad not-a-number\n")


def test_signals_from_health_parses_server_shape(params):
    """Schema lock against the REAL /health payload: a field rename in
    runtime/server.py must break here, not silently in a router."""
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, prefill_chunk=4,
                          page_size=4, kv_pages=16)
    srv.start()
    try:
        body = json.dumps({"prompt": "abcdef", "steps": 8}).encode()
        rq = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(rq, timeout=60) as r:
            assert json.loads(r.read())["steps"] > 0
        row = scrape_replica("r0", f"http://127.0.0.1:{srv.port}")
        assert row.healthy and row.state == "serving"
        assert row.slots == 2
        assert row.kv_pages == 16 and row.kv_pages_free > 0
        assert row.generated_tokens > 0 and row.steps > 0
        assert row.prefix_hits + row.prefix_misses >= 1
        # the dead-replica path: a closed port reports unhealthy
        dead = scrape_replica("r1", "http://127.0.0.1:1")
        assert not dead.healthy and dead.error
        agg = rollup([row, dead])
        assert agg.healthy == 1 and agg.replicas == 2
    finally:
        srv.stop()


# ------------------------------------------------------ fleetcheck (sim)


def _sim_args(n=2, seed=7, requests=12):
    return argparse.Namespace(sim=n, seed=seed, rate=0.4,
                              requests=requests, arrivals="bursty",
                              slots=2, page_size=4, kv_pages=12,
                              spec_k=0, block_steps=1, replicas=None,
                              timeout=5.0, stale_after=None,
                              json=True)


def test_fleetcheck_sim_deterministic_and_consistent():
    """The CI property: same seed ⇒ identical rows + rollup; the rollup
    is the recomputed sum of its rows (run_sim's own self-check passes
    clean)."""
    import fleetcheck

    results = []
    for _ in range(2):
        rows, agg, failures, tower = fleetcheck.run_sim(_sim_args())
        assert failures == []
        results.append(([r.to_json() for r in rows], agg.to_json(),
                        tower.to_json(tail=0)))
    assert results[0] == results[1]
    rows_json, agg_json, watch_json = results[0]
    assert len(rows_json) == 2
    assert agg_json["generated_tokens"] == sum(
        r["generated_tokens"] for r in rows_json)
    assert agg_json["kv_pages_free"] == sum(
        r["kv_pages_free"] for r in rows_json)
    # the shared watchtower saw every replica tick, and a clean sim
    # raises no incidents (the detection matrix is watchcheck's gate)
    assert watch_json["ticks"] > 0
    assert watch_json["incidents_total"] == 0
    # a different seed genuinely changes the row (the gate is not
    # vacuously comparing constants)
    rows2, agg2, _, _ = fleetcheck.run_sim(_sim_args(seed=8))
    assert agg2.to_json() != agg_json


def test_fleetcheck_cli_exit_codes(tmp_path, capsys):
    import fleetcheck

    assert fleetcheck.main([]) == 2  # neither mode picked
    assert fleetcheck.main(["--sim", "2", "--replicas", "x"]) == 2
    rc = fleetcheck.main(["--sim", "2", "--seed", "7", "--requests",
                          "8", "--json"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    row = json.loads(out)
    assert rc == 0
    assert row["kind"] == "fleetcheck"
    assert row["gate"]["verdict"] == "OK"
    assert row["rollup"]["healthy"] == 2
    assert len(row["rows"]) == 2
    assert "env_fingerprint" in row  # joinable with BENCH_* rows


def test_rollup_cost_columns_recomputed_from_sums():
    """ISSUE 16: per-class cost-per-token comes from Σ compute seconds /
    Σ tokens across replicas — never the mean of per-replica ratios."""
    cheap = _row("cheap", cost_classes={"interactive": {
        "tokens": 90, "requests": 9, "compute_s": 9.0, "page_s": 18.0,
        "stall_s_total": 0.0}}, page_seconds=18.0,
        stall_seconds={"queue_wait": 1.0})
    dear = _row("dear", cost_classes={"interactive": {
        "tokens": 10, "requests": 1, "compute_s": 11.0, "page_s": 2.0,
        "stall_s_total": 3.0}}, page_seconds=2.0,
        stall_seconds={"queue_wait": 0.5, "pool_dry": 2.0})
    agg = rollup([cheap, dear])
    cell = agg.cost["interactive"]
    # (9+11)/(90+10) = 0.2 — mean-of-ratios would say (0.1+1.1)/2 = 0.6
    assert cell["cost_per_token_s"] == pytest.approx(0.2)
    assert cell["page_s_per_token"] == pytest.approx(0.2)
    assert cell["stall_seconds"] == pytest.approx(3.0)
    assert agg.page_seconds == pytest.approx(20.0)
    assert agg.stall_seconds == {"queue_wait": 1.5, "pool_dry": 2.0}
    # cost per GOODPUT token: Σ compute / Σ goodput (60 + 60 from _row)
    assert agg.cost_per_goodput_token == pytest.approx(20.0 / 120.0)
    j = agg.to_json()
    assert j["cost"]["interactive"]["cost_per_token_s"] == 0.2
    assert j["cost_per_goodput_token"] == pytest.approx(1 / 6)


def test_signals_from_health_parses_sched_block():
    payload = {"state": "serving", "slots": 4,
               "sched": {"cost_totals": {"page_s": 2.5,
                                         "stall_s": {"pool_dry": 0.25}},
                         "cost_by_class": {"batch": {
                             "tokens": 40, "requests": 4,
                             "compute_s": 1.5, "page_s": 2.5,
                             "stall_s_total": 0.25, "page_steps": 50}}}}
    row = signals_from_health("r", payload)
    assert row.page_seconds == 2.5
    assert row.stall_seconds == {"pool_dry": 0.25}
    assert row.cost_classes["batch"]["compute_s"] == 1.5
    # pre-ledger servers: no block, zero cost columns, no crash
    bare = signals_from_health("old", {"state": "serving"})
    assert bare.page_seconds == 0.0 and bare.cost_classes == {}


# ------------------------------------------- staleness + spans_dropped


def test_rollup_stale_rows_excluded_from_sums():
    """ISSUE 20 satellite: a healthy row whose scrape stamp aged past
    stale_after counts in `stale` only — its last-known numbers feed
    nothing, but it is not reported as a dead box either."""
    fresh = _row("fresh", scraped_at=100.0)
    old = _row("old", scraped_at=10.0)
    agg = rollup([fresh, old], stale_after=30.0, now=105.0)
    assert agg.replicas == 2
    assert agg.healthy == 1 and agg.stale == 1
    assert agg.slots == 4  # only the fresh row summed
    assert agg.goodput_tokens == 60
    # without a stale_after the same rows all count (opt-in knob)
    assert rollup([fresh, old], now=105.0).healthy == 2
    # unstamped rows (tests, sims) are never stale
    agg2 = rollup([_row("direct")], stale_after=1.0, now=1e9)
    assert agg2.healthy == 1 and agg2.stale == 0
    # an unhealthy stale row stays counted as unhealthy, not stale
    dead = ReplicaSignals(name="dead", healthy=False, error="refused",
                          scraped_at=10.0)
    agg3 = rollup([fresh, dead], stale_after=30.0, now=105.0)
    assert agg3.healthy == 1 and agg3.stale == 0


def test_spans_dropped_cross_fill_and_fleet_sum():
    """ISSUE 20 satellite: dllama_spans_dropped_total cross-fills the
    row from /metrics and sums fleet-wide — the 'can the fleet's
    incident timelines be trusted' column."""
    row = apply_metrics(ReplicaSignals(name="a"),
                        parse_metrics("dllama_spans_dropped_total 5\n"))
    assert row.spans_dropped == 5
    agg = rollup([row, _row("b", spans_dropped=2)])
    assert agg.spans_dropped == 7
    assert agg.to_json()["spans_dropped"] == 7
    # a stale row's drops are excluded like every other sum
    stale = _row("c", spans_dropped=100, scraped_at=0.0)
    agg2 = rollup([row, stale], stale_after=1.0, now=100.0)
    assert agg2.spans_dropped == 5


def test_scrape_replica_stamps_scraped_at(params):
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)
    srv.start()
    try:
        row = scrape_replica("r0", f"http://127.0.0.1:{srv.port}",
                             timeout=10.0)
        assert row.healthy and row.scraped_at is not None
        # the stamp rides to_json (None for direct-built rows)
        assert row.to_json()["scraped_at"] == pytest.approx(
            row.scraped_at, abs=1e-3)
        assert ReplicaSignals(name="x").to_json()["scraped_at"] is None
        # error rows are stamped too — age and death are orthogonal
        dead = scrape_replica("r1", "http://127.0.0.1:1", timeout=2.0)
        assert not dead.healthy and dead.scraped_at is not None
    finally:
        srv.stop()
