"""threadcheck rule fixtures (ISSUE 17): every T-rule gets a firing, a
non-firing, and a pragma-suppressed snippet, plus the registry
self-check and the baseline round-trip on threadcheck findings.

Fixture modules are written under a fake package layout (tmp/runtime/...)
so the runtime/+obs/ scoping is exercised exactly as on the real tree,
and they name REAL registered classes (ContinuousEngine, RequestJournal,
PageUploader) so domain propagation and family lookup run against the
production threadmodel registry. The checker is pure AST — none of these
snippets is ever imported or executed."""

from __future__ import annotations

import textwrap
from pathlib import Path

from distributed_llama_tpu.analysis.lint import (apply_baseline,
                                                 load_baseline,
                                                 write_baseline)
from distributed_llama_tpu.analysis.threadcheck import (THREAD_RULES,
                                                        run_threadcheck,
                                                        thread_scope)
from distributed_llama_tpu.analysis.threadmodel import (ENTRYPOINTS,
                                                        FAMILIES, validate)


def run_on(tmp_path: Path, rel: str, source: str):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    return run_threadcheck([path], tmp_path)


def rules_fired(findings):
    return {f.rule for f in findings}


# -- registry self-consistency ---------------------------------------------


def test_threadmodel_registry_validates():
    assert validate() == []


def test_registry_covers_the_core_surfaces():
    classes = {f.owner_class for f in FAMILIES}
    for cls in ("ContinuousEngine", "PagedAllocator", "RequestJournal",
                "LedgerBook", "InferenceServer", "FlightRecorder"):
        assert cls in classes, f"{cls} has no declared attr family"
    assert "InferenceServer._scheduler" in ENTRYPOINTS
    assert "PageUploader._run" in ENTRYPOINTS


def test_scope_is_runtime_and_obs_only():
    assert thread_scope("distributed_llama_tpu/runtime/continuous.py")
    assert thread_scope("distributed_llama_tpu/obs/ledger.py")
    assert not thread_scope("distributed_llama_tpu/models/llama.py")
    assert not thread_scope("tools/racecheck.py")


# -- T001: cross-domain write without the declared lock --------------------


def test_t001_fires_on_unlocked_family_write(tmp_path):
    findings = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def poke(self, req):
                self._queue.append(req)
    """)
    assert [f.rule for f in findings] == ["T001"]
    assert "_lock" in findings[0].message


def test_t001_quiet_under_the_declared_lock_and_in_init(tmp_path):
    assert run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def __init__(self):
                self._queue = []

            def poke(self, req):
                with self._lock:
                    self._queue.append(req)
    """) == []


def test_t001_pragma_suppresses_with_reason(tmp_path):
    assert run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def poke(self, req):
                self._queue.append(req)  # threadcheck: allow[T001] quiesced
    """) == []


def test_t001_out_of_scope_module_is_ignored(tmp_path):
    # the same hazard outside runtime/+obs/ is not threadcheck's beat
    assert run_on(tmp_path, "models/eng.py", """
        class ContinuousEngine:
            def poke(self, req):
                self._queue.append(req)
    """) == []


# -- T002: lock-order inversion --------------------------------------------


def test_t002_fires_on_inverted_acquisition_order(tmp_path):
    findings = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def fwd(self):
                with self._lock:
                    with self._book._lock:
                        pass

            def rev(self):
                with self._book._lock:
                    with self._lock:
                        pass
    """)
    assert "T002" in rules_fired(findings)


def test_t002_quiet_on_consistent_order(tmp_path):
    findings = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def fwd(self):
                with self._lock:
                    with self._book._lock:
                        pass

            def also_fwd(self):
                with self._lock:
                    with self._book._lock:
                        pass
    """)
    assert "T002" not in rules_fired(findings)


def test_t002_pragma_suppresses(tmp_path):
    findings = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def fwd(self):
                with self._lock:
                    with self._book._lock:
                        pass

            def rev(self):
                with self._book._lock:
                    # threadcheck: allow[T002] teardown-only path
                    with self._lock:
                        pass
    """)
    assert "T002" not in rules_fired(findings)


# -- T003: blocking call while holding a lock ------------------------------


def test_t003_fires_on_fsync_under_lock(tmp_path):
    findings = run_on(tmp_path, "runtime/j.py", """
        import os

        class RequestJournal:
            def flush(self):
                with self._lock:
                    os.fsync(self._fh.fileno())
    """)
    assert "T003" in rules_fired(findings)


def test_t003_quiet_outside_lock_and_for_str_join(tmp_path):
    assert run_on(tmp_path, "runtime/j.py", """
        import os

        class RequestJournal:
            def flush(self):
                with self._lock:
                    names = ", ".join(self._names)
                    path = os.path.join("a", "b")
                os.fsync(self._fh.fileno())
                return names, path
    """) == []


def test_t003_pragma_suppresses_with_reason(tmp_path):
    assert run_on(tmp_path, "runtime/j.py", """
        import os

        class RequestJournal:
            def flush(self):
                with self._lock:
                    os.fsync(self._fh.fileno())  # threadcheck: allow[T003] WAL durability point
    """) == []


# -- T004: thread started outside the entrypoint registry ------------------


def test_t004_fires_on_unregistered_thread_target(tmp_path):
    findings = run_on(tmp_path, "runtime/spawn.py", """
        import threading

        def kick(worker):
            t = threading.Thread(target=worker_loop, daemon=True)
            t.start()
            return t
    """)
    assert "T004" in rules_fired(findings)


def test_t004_quiet_on_registered_targets_incl_loop_bound(tmp_path):
    # both direct method targets and the `for target in (...)` idiom
    # the server's start() uses must resolve through the registry
    assert run_on(tmp_path, "runtime/spawn.py", """
        import threading

        class InferenceServer:
            def start(self):
                for target in (self._scheduler, self.httpd.serve_forever):
                    t = threading.Thread(target=target, daemon=True)
                    t.start()
    """) == []


def test_t004_pragma_suppresses(tmp_path):
    assert run_on(tmp_path, "runtime/spawn.py", """
        import threading

        def kick():
            t = threading.Thread(target=worker_loop)  # threadcheck: allow[T004] drill-local
            t.start()
    """) == []


# -- T005: mutable family state escaping its domain ------------------------


def test_t005_fires_on_raw_return_to_foreign_domain(tmp_path):
    # submit is a declared cross-domain crossing point: handing the raw
    # queue back to a handler thread escapes scheduler-owned state
    findings = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def submit(self, req):
                return self._queue
    """)
    assert "T005" in rules_fired(findings)


def test_t005_quiet_on_snapshot_return(tmp_path):
    findings = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def submit(self, req):
                with self._lock:
                    return list(self._queue)
    """)
    assert "T005" not in rules_fired(findings)


def test_t005_pragma_suppresses(tmp_path):
    findings = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def submit(self, req):
                return self._queue  # threadcheck: allow[T005] caller holds _lock
    """)
    assert "T005" not in rules_fired(findings)


# -- T000 + baseline machinery ---------------------------------------------


def test_unreadable_in_scope_file_is_a_finding(tmp_path):
    bad = tmp_path / "runtime" / "broken.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("def broken(:\n", encoding="utf-8")
    findings = run_threadcheck([bad], tmp_path)
    assert [f.rule for f in findings] == ["T000"]


def test_every_rule_has_a_title_and_hint():
    for rule, (title, hint) in THREAD_RULES.items():
        assert title and hint, rule


def test_baseline_round_trip_on_threadcheck_findings(tmp_path):
    findings = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def poke(self, req):
                self._queue.append(req)
    """)
    assert findings
    baseline_path = tmp_path / "tb.txt"
    write_baseline(baseline_path, findings)
    baseline = load_baseline(baseline_path)
    new, suppressed, stale = apply_baseline(findings, baseline)
    assert (new, suppressed, stale) == ([], len(findings), [])
    # fixing the finding turns the entry stale (line-number independent)
    fixed = run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def poke(self, req):
                with self._lock:
                    self._queue.append(req)
    """)
    new, suppressed, stale = apply_baseline(fixed, baseline)
    assert new == [] and suppressed == 0 and len(stale) == len(findings)


def test_dlint_and_threadcheck_pragmas_coexist_on_one_line(tmp_path):
    # the shared pragma parser: either tag may carry either head's rule
    # ids (namespaces are disjoint), and one line can carry both tags
    assert run_on(tmp_path, "runtime/eng.py", """
        class ContinuousEngine:
            def poke(self, req):
                self._queue.append(req)  # dlint: allow[D007] x  # threadcheck: allow[T001] y
    """) == []
