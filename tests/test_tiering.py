"""Hierarchical KV tiering (ISSUE 12): HBM ⇄ host RAM ⇄ disk.

Four layers of gates:

* pure-host units: HostPagePool free-list/ownership invariants,
  DiskPageStore CRC'd store/load round-trips, strict-LRU demotion order;
* tier-invariant properties: demote→promote round-trips are BYTE-exact
  (f32 bitwise, Q8 code-exact — the payload is the page wire layout, no
  re-encode anywhere on the path), a CRC-damaged disk page re-derives
  via prefill instead of crashing, and the three-tier audit closes the
  ledger after arbitrary churn;
* scheduler semantics: admission PAUSEs until the async promotion upload
  lands (pages-starved semantics; pinned deterministically by gating the
  PageUploader), and streams are bitwise invisible to tiering;
* the capacity claim: at a working set ~10x the HBM pool, prefix-hit
  prefill savings hold at the all-HBM ceiling while the drop-on-evict
  baseline recomputes everything.
"""

import os
import threading

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.runtime.paging import (DiskPageStore,
                                                  HostPagePool,
                                                  PagedAllocator,
                                                  TIER_DISK, TIER_HBM,
                                                  TIER_HOST)

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=32)
PS = 4


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


def _engine(params, **kw):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    base = dict(slots=2, temperature=0.0, topp=0.9, seed=3,
                prefill_chunk=PS, page_size=PS)
    base.update(kw)
    return ContinuousEngine(SPEC, params, **base)


def _waves(n_prefix, tails=(3, 9)):
    """Two passes over n_prefix distinct 2-page shared prefixes: pass 1
    publishes, pass 2 revisits every one (by then cold prefixes have
    spilled — or died, on a drop-on-evict pool)."""
    return [[[1] + [(7 * i + j) % 90 + 5 for j in range(2 * PS)]
             + [t + i % 40] for i in range(n_prefix)] for t in tails]


# -- HostPagePool -----------------------------------------------------------


def test_host_pool_ids_lowest_first_and_accounting():
    pool = HostPagePool(3)
    a = pool.store(("a",))
    b = pool.store(("b",))
    assert (a, b) == (0, 1)
    assert pool.load(a) == ("a",)
    assert pool.free(a) == ("a",)
    assert pool.store(("c",)) == 0  # freed id reused, lowest-first
    pool.store(("d",))
    assert pool.store(("overflow",)) is None  # full reports, not raises
    assert pool.n_free == 0 and pool.n_live == 3
    assert pool.audit() == []


# -- DiskPageStore ----------------------------------------------------------


def _payload(seed):
    rng = np.random.RandomState(seed)
    return (rng.randn(2, PS, 2, 16).astype(np.float32),
            rng.randn(2, PS, 2, 16).astype(np.float32))


def test_disk_store_round_trip_bitwise(tmp_path):
    store = DiskPageStore(str(tmp_path))
    p = _payload(0)
    ref = store.store(p)
    got = store.load(ref)
    assert all(np.array_equal(a, b) and a.dtype == b.dtype
               for a, b in zip(got, p))
    assert store.audit() == []
    store.free(ref)
    assert not store.live(ref)


def test_disk_store_crc_corruption_loads_none(tmp_path):
    store = DiskPageStore(str(tmp_path))
    ref = store.store(_payload(1))
    path, off = ref[0], ref[1]
    with open(path, "r+b") as fh:
        fh.seek(off + 5)
        byte = fh.read(1)
        fh.seek(off + 5)
        fh.write(bytes([byte[0] ^ 0xFF]))
    assert store.load(ref) is None  # damage -> None, never wrong bytes
    assert store.crc_failures == 1
    assert store.audit() != []  # the audit's read-back flags it too


def test_disk_store_budget_and_dead_segment_reclaim(tmp_path):
    store = DiskPageStore(str(tmp_path), budget_bytes=3000)
    small = (np.zeros(256, np.float32),)  # 1024 B records
    r1, r2 = store.store(small), store.store(small)
    assert store.store(small) is None  # budget: 3072 > 3000
    store.free(r1)
    r3 = store.store(small)
    assert r3 is not None
    # a fully-dead sealed segment unlinks (bound append-only growth):
    # rotate to a fresh segment, then kill every record in the old one
    store.SEGMENT_BYTES = 1  # every store from now on seals + rotates
    store.free(r3)
    r4 = store.store(small)
    assert r4 is not None and r4[0] != r2[0]  # rotated
    seg1 = r2[0]
    store.free(r2)  # last live record of segment 1 dies
    assert not os.path.exists(seg1)
    assert store.live(r4) and store.audit() == []


# -- strict LRU -------------------------------------------------------------


def test_demotion_order_is_strict_lru():
    """Per-touch monotonic ticks: demotion victims leave in exact
    recency order even when published in one insert batch."""
    alloc = PagedAllocator(8, 2, host_pages=8)
    alloc.bind_device_io(lambda pid: (np.full((1,), pid, np.float32),))
    pages = [alloc.alloc_page() for _ in range(3)]
    alloc.insert_prefix([1, 2, 3, 4, 5, 6], pages)  # 3 nodes, one insert
    alloc.release_pages(pages)
    # touch the MIDDLE window's chain only: [1,2] then [3,4] refresh
    got = alloc.match_prefix([1, 2, 3, 4])
    alloc.release_pages(got)
    order = []
    orig_store = alloc.host.store

    def spy(payload):
        order.append(int(payload[0][0]))
        return orig_store(payload)

    alloc.host.store = spy
    alloc.demote_cold(3)
    # LRU = the untouched deepest window first (oldest tick), then the
    # refreshed chain bottom-up by touch order
    assert order == [pages[2], pages[0], pages[1]]
    assert alloc.audit([]) == []


# -- tier-invariant properties ----------------------------------------------


def test_demote_promote_round_trip_bitwise_f32():
    """HBM -> host -> disk -> HBM moves the exact page bytes: the staged
    promotion payload is bit-identical to what demotion fetched."""
    alloc = PagedAllocator(2, 2, host_pages=1)
    payloads = {}

    def fetch(pid):
        payloads[pid] = _payload(pid)
        return payloads[pid]

    alloc.bind_device_io(fetch)
    pages = [alloc.alloc_page(), alloc.alloc_page()]
    alloc.insert_prefix([1, 2, 3, 4], pages)
    alloc.release_pages(pages)
    alloc.demote_cold(2)  # both out of HBM; host holds 1, 1 dropped
    assert alloc.tier_page_counts()[TIER_HOST] == 1
    got = alloc.match_prefix([1, 2, 3, 4])
    assert len(got) >= 1
    jobs = alloc.take_staged_promotions()
    for job in jobs:
        orig = payloads[pages[0]]
        assert all(np.array_equal(a, b) for a, b in zip(job.staged, orig))
        alloc.promotion_applied(job)
    alloc.release_pages(got)
    assert alloc.audit([]) == []


def test_demote_promote_round_trip_bitwise_through_disk(tmp_path):
    alloc = PagedAllocator(2, 2, disk_dir=str(tmp_path))
    payloads = {}

    def fetch(pid):
        payloads[pid] = _payload(100 + pid)
        return payloads[pid]

    alloc.bind_device_io(fetch)
    pages = [alloc.alloc_page()]
    alloc.insert_prefix([1, 2], pages)
    alloc.release_pages(pages)
    alloc.demote_cold(1)
    assert alloc.tier_page_counts()[TIER_DISK] == 1
    got = alloc.match_prefix([1, 2])
    (job,) = alloc.take_staged_promotions()
    assert all(np.array_equal(a, b) and a.dtype == b.dtype
               for a, b in zip(job.staged, payloads[pages[0]]))
    alloc.promotion_applied(job)
    alloc.release_pages(got)
    assert alloc.audit([]) == []


def test_engine_streams_invisible_to_tiering_f32(params, tmp_path):
    """The whole-engine parity gate: a three-tier engine under heavy
    spill churn emits BITWISE the streams of an all-HBM engine — and the
    drop-on-evict baseline proves the savings are real, not residual."""
    w1, w2 = _waves(8)
    ref = _engine(params, kv_pages=64)
    r1, _ = ref.run(w1, steps=16)
    r2, _ = ref.run(w2, steps=16)
    ceiling = ref.allocator.tokens_saved

    eng = _engine(params, kv_pages=8, kv_host_pages=6,
                  kv_disk_dir=str(tmp_path))
    t1, _ = eng.run(w1, steps=16)
    eng.allocator.reset_counters()
    t2, _ = eng.run(w2, steps=16)
    a = eng.allocator
    assert (t1, t2) == (r1, r2)
    assert sum(a.demotions.values()) > 0
    assert sum(a.promotions.values()) > 0
    assert (a.tokens_saved_by_tier[TIER_HOST]
            + a.tokens_saved_by_tier[TIER_DISK]) > 0
    assert eng.audit_pages() == []

    drop = _engine(params, kv_pages=8)
    d1, _ = drop.run(w1, steps=16)
    drop.allocator.reset_counters()
    d2, _ = drop.run(w2, steps=16)
    assert (d1, d2) == (r1, r2)
    assert ceiling > 0 and drop.allocator.tokens_saved == 0


def test_engine_q8_pages_value_exact_through_tiers(params, tmp_path):
    """Q8 pools spill their CODES+DELTAS verbatim: a tiered q8 engine's
    greedy streams equal the untiered q8 engine's exactly (the payload
    is never re-quantized on the demote/promote path)."""
    w1, w2 = _waves(8)
    ref = _engine(params, kv_pages=64, kv_quant="q8")
    r1, _ = ref.run(w1, steps=16)
    r2, _ = ref.run(w2, steps=16)
    eng = _engine(params, kv_pages=8, kv_host_pages=6, kv_quant="q8",
                  kv_disk_dir=str(tmp_path))
    t1, _ = eng.run(w1, steps=16)
    t2, _ = eng.run(w2, steps=16)
    assert (t1, t2) == (r1, r2)
    assert sum(eng.allocator.promotions.values()) > 0
    assert eng.audit_pages() == []


def test_engine_streams_invisible_under_tp_mesh(params, tmp_path):
    """ISSUE 12's tp leg: sharded pool planes demote through the same
    fetch (np gather over the sharded page) and promote through
    parallel/tp.stage_page_planes (payload device_put pre-sharded on the
    kv-head axis) — streams stay bitwise the single-chip run's."""
    from distributed_llama_tpu.parallel import make_mesh

    w1, w2 = _waves(6)
    ref = _engine(params, kv_pages=64)
    r1, _ = ref.run(w1, steps=16)
    r2, _ = ref.run(w2, steps=16)
    eng = _engine(params, kv_pages=8, kv_host_pages=6,
                  kv_disk_dir=str(tmp_path), mesh=make_mesh(tp=2))
    t1, _ = eng.run(w1, steps=16)
    t2, _ = eng.run(w2, steps=16)
    assert (t1, t2) == (r1, r2)
    assert sum(eng.allocator.promotions.values()) > 0
    assert eng.audit_pages() == []


def test_disk_crc_corruption_rederives_via_prefill(params, tmp_path):
    """A CRC-damaged disk page must degrade to recompute: the hit falls
    back to prefill, streams stay correct, nothing crashes, and the
    audit is clean afterwards (the dead record is dropped)."""
    w1, w2 = _waves(6)
    ref = _engine(params, kv_pages=64)
    r1, _ = ref.run(w1, steps=16)
    r2, _ = ref.run(w2, steps=16)

    # disk-only tier so every demotion lands in a segment file
    eng = _engine(params, kv_pages=8, kv_disk_dir=str(tmp_path))
    t1, _ = eng.run(w1, steps=16)
    a = eng.allocator
    assert a.tier_page_counts()[TIER_DISK] > 0
    # smash one byte in every live record of every segment
    for (path, off), length in list(a.disk._live.items()):
        with open(path, "r+b") as fh:
            fh.seek(off + length // 2)
            byte = fh.read(1)
            fh.seek(off + length // 2)
            fh.write(bytes([byte[0] ^ 0xFF]))
    t2, _ = eng.run(w2, steps=16)
    assert (t1, t2) == (r1, r2)  # re-derived, not wrong, not crashed
    assert a.crc_drops > 0
    assert eng.audit_pages() == []


# -- admission PAUSE until promoted -----------------------------------------


def test_admission_pauses_until_promotion_lands(params, tmp_path):
    """Hold the PageUploader's gate: a request whose shared prefix is
    mid-promotion rides dispatches masked inactive (stats.pauses moves,
    no tokens sample) and resumes bitwise once the upload lands."""
    from distributed_llama_tpu.runtime.continuous import Request

    prefix = [1] + [11 + j for j in range(2 * PS)]
    ref = _engine(params, kv_pages=64)
    (want,), _ = ref.run([prefix + [99]], steps=16)

    eng = _engine(params, kv_pages=8, kv_host_pages=8,
                  kv_disk_dir=str(tmp_path))
    eng.run([prefix + [42]], steps=16)  # publish the prefix
    assert eng.allocator.demote_cold(2) == 2  # spill it
    gate = threading.Event()  # held: staging stalls
    eng._uploader.gate = gate
    req = Request(tokens=prefix + [99], steps=16)
    eng.submit(req)
    pauses0 = eng.stats.pauses
    for _ in range(3):
        eng.step_once()
    assert not req.done.is_set()
    assert eng.stats.pauses > pauses0  # rode dispatches masked inactive
    assert req.n_sampled == 0  # nothing sampled while paused
    slot = next(s for s in eng._pool if s.req is req)
    assert eng.allocator.slot_pending(slot.pages)
    gate.set()
    for _ in range(200):
        if eng.step_once() == 0:
            break
    assert req.done.is_set() and req.error is None
    assert req.out == want  # bitwise the all-HBM stream
    assert sum(eng.allocator.promotions.values()) >= 2
    assert eng.audit_pages() == []


# -- working-set sweep ------------------------------------------------------


def test_savings_hold_at_10x_hbm_working_set(params, tmp_path):
    """The ISSUE 12 acceptance shape: 20 prefixes x 2 pages = 40 prefix
    pages against an 8-page pool (10x with the tails) — tiered savings
    within 20% of the all-HBM ceiling, drop baseline at zero."""
    w1, w2 = _waves(20)
    ref = _engine(params, kv_pages=64)
    ref.run(w1, steps=16)
    ref.allocator.reset_counters()
    ref.run(w2, steps=16)
    ceiling = ref.allocator.tokens_saved
    assert ceiling == 20 * 2 * PS  # every prefix re-hit in full

    eng = _engine(params, kv_pages=8, kv_host_pages=10,
                  kv_disk_dir=str(tmp_path))
    eng.run(w1, steps=16)
    eng.allocator.reset_counters()
    eng.run(w2, steps=16)
    assert eng.allocator.tokens_saved >= 0.8 * ceiling
    assert eng.audit_pages() == []

    drop = _engine(params, kv_pages=8)
    drop.run(w1, steps=16)
    drop.allocator.reset_counters()
    drop.run(w2, steps=16)
    assert drop.allocator.tokens_saved <= 0.2 * ceiling


# -- observability ----------------------------------------------------------


def test_tier_metrics_exposition_and_counters(params, tmp_path):
    from distributed_llama_tpu.obs.metrics import Registry

    reg = Registry()
    eng = _engine(params, kv_pages=8, kv_host_pages=6,
                  kv_disk_dir=str(tmp_path), metrics=reg)
    w1, w2 = _waves(8)
    eng.run(w1, steps=16)
    eng.run(w2, steps=16)
    text = reg.expose()
    assert 'dllama_kv_tier_pages{tier="host"}' in text
    assert "dllama_tier_promotions_total" in text
    assert "dllama_tier_demotions_total" in text
    assert 'dllama_prefill_tokens_saved_by_tier_total{tier="disk"}' in text
    a = eng.allocator

    def sample(name):
        for line in text.splitlines():
            if line.startswith(name) and not line.startswith("#"):
                return float(line.rsplit(" ", 1)[1])
        raise AssertionError(f"{name} not exposed")

    assert sample("dllama_tier_promotions_total") == sum(
        a.promotions.values())
    assert sample("dllama_tier_demotions_total") == sum(
        a.demotions.values())


def test_untiered_engine_exposes_tier_series_flat(params):
    """Layout-invariant scrape surface: no tiers -> the series exist at
    zero and never move (dashboards survive the knob)."""
    from distributed_llama_tpu.obs.metrics import Registry

    reg = Registry()
    eng = _engine(params, kv_pages=16, metrics=reg)
    eng.run(_waves(3)[0], steps=12)
    text = reg.expose()
    assert "dllama_tier_promotions_total 0" in text
    assert "dllama_tier_demotions_total 0" in text


# -- knob validation --------------------------------------------------------


def test_tier_knobs_require_paged_cache(params):
    with pytest.raises(ValueError, match="kv-page-size"):
        _engine(params, page_size=0, kv_host_pages=4)
    with pytest.raises(ValueError, match="kv_disk_dir"):
        _engine(params, kv_disk_bytes=1 << 20)
