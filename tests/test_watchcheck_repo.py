"""watchcheck gate (ISSUE 20): the detection matrix holds against THIS
repo — each chaos fault raises exactly its incident kind, the healthy
sweep raises none, the row is byte-deterministic, and both mutation
arms turn the gate red."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

import watchcheck  # noqa: E402

MATRIX = {
    "healthy": None,
    "leak-on-cancel": "page_leak",
    "deny-pages-storm": "stall_shift",
    "kill-mid-decode-loop": "recovery_storm",
    "drop-page-in-flight": "handoff_spike",
}


@pytest.fixture(scope="module")
def row(tmp_path_factory):
    """One clean watchcheck run, shared across tests (the scenarios
    replay whole engine workloads — run them once)."""
    import argparse

    args = argparse.Namespace(seed=7, json=True, inject=None)
    return watchcheck.run(args)


def test_detection_matrix_green(row):
    assert row["gate"]["ok"] is True
    assert row["gate"]["failures"] == []
    by_name = {s["name"]: s for s in row["scenarios"]}
    assert set(by_name) == set(MATRIX)
    for name, expect in MATRIX.items():
        s = by_name[name]
        assert s["ok"], s
        assert s["expect"] == expect
        if expect is None:
            # the false-positive gate: a healthy sweep raises NOTHING
            assert s["incidents"] == []
        else:
            kinds = {i["kind"] for i in s["incidents"]}
            assert kinds == {expect}, (name, kinds)
            assert s["fired_tick"] is not None
            assert s["fired_tick"] <= s["detect_by"]


def test_row_is_fingerprint_stamped(row):
    assert row["kind"] == "watchcheck"
    assert "env_fingerprint" in row  # joinable with BENCH_* rows
    assert row["config"]["seed"] == 7
    assert row["thresholds"] == dict(
        __import__("distributed_llama_tpu.obs.watch",
                   fromlist=["THRESHOLDS"]).THRESHOLDS)


def test_two_runs_byte_identical(row):
    import argparse

    again = watchcheck.run(argparse.Namespace(seed=7, json=True,
                                              inject=None))
    assert (json.dumps(again, sort_keys=True)
            == json.dumps(row, sort_keys=True))


def test_mutation_arms_turn_the_gate_red(capsys):
    """mute-detector blinds each fault scenario's expected kind (faults
    go undetected); jitter-thresholds makes the healthy sweep page.
    Both must exit exactly 1 — the gate can actually fail."""
    for inject in ("mute-detector", "jitter-thresholds"):
        rc = watchcheck.main(["--seed", "7", "--json",
                              "--inject", inject])
        out = capsys.readouterr().out.strip().splitlines()[-1]
        red = json.loads(out)
        assert rc == 1, inject
        assert red["gate"]["ok"] is False
        assert red["gate"]["failures"], inject
    assert watchcheck.main(["--inject", "nonsense"]) == 2
