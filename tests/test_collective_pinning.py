"""Pin the analytic ICI model to the compiled program (VERDICT r4 #4).

Every multi-chip performance number in this repo carries an ICI term built
from `comm_stats.ici_all_gather_bytes` (payload) and shard_sim's
`n_coll = 4*L + 1` (collective count). Until now those were asserted only
by the same arithmetic that produced them. These tests derive BOTH numbers
independently from the program itself:

  * jaxpr level — trace `make_sharded_forward` for the REAL 7B/13B/70B
    specs (abstract params; nothing is materialized) on the virtual
    8-device mesh, walk the equation graph with scan-length multiplicity,
    and count every collective primitive with its per-shard payload aval.
  * compiled level — lower + compile the 7B program on the CPU mesh and
    count the all-gather instructions XLA actually emitted.

If the traced program ever gains/loses a collective, changes a payload
dtype (e.g. the Q80 wire packing), or the analytic model drifts from what
the program does, these fail. Anchors: the projection model feeds the 70B
north-star claim vs README.md:48; the reference's own published
transfer-per-token tables are README.md:58-69.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_llama_tpu.io.loader import Q40Weight
from distributed_llama_tpu.models.llama import init_cache
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import (_build_tree, llama2_7b_spec,
                                                llama2_13b_spec,
                                                llama2_70b_spec,
                                                small_bench_spec)
from distributed_llama_tpu.ops.quants import FloatType, batch_bytes
from distributed_llama_tpu.parallel import make_mesh, make_sharded_forward
from distributed_llama_tpu.parallel.comm_stats import ici_all_gather_bytes


def _abstract_params(spec: TransformerSpec):
    """The full-size param tree as avals only — 70B traces in seconds and
    materializes nothing."""
    def t(*shape):
        return jnp.zeros(shape, jnp.float32)

    def mm(*shape):
        *lead, d, n = shape
        return Q40Weight(jnp.zeros((*lead, d, n // 32, 16), jnp.uint8),
                         jnp.zeros((*lead, d, n // 32), jnp.float16))

    return jax.eval_shape(lambda: _build_tree(spec, t, mm))


def _collect_collectives(jaxpr, mult=1):
    """[(primitive_name, per_shard_aval, multiplicity)] for every
    collective eqn, weighting eqns inside scan bodies by trip count (the
    layer loop appears ONCE in the jaxpr but runs n_layers times)."""
    out = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        m = mult
        if name == "scan":
            m = mult * eqn.params["length"]
        if name.startswith(("all_gather", "all_to_all", "psum", "pmax",
                            "pmin", "ppermute", "reduce_scatter")):
            out.append((name, eqn.invars[0].aval, mult))
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if hasattr(v, "eqns"):
                out.extend(_collect_collectives(v, m))
            elif inner is not None and hasattr(inner, "eqns"):
                out.extend(_collect_collectives(inner, m))
    return out


def _trace_collectives(spec: TransformerSpec, tp: int):
    mesh = make_mesh(tp=tp)
    fwd = make_sharded_forward(spec, mesh)
    params = _abstract_params(spec)
    cache = jax.eval_shape(lambda: init_cache(spec, jnp.float32))
    tokens = jax.ShapeDtypeStruct((1,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    jaxpr = jax.make_jaxpr(fwd)(params, cache, tokens, pos).jaxpr
    colls = _collect_collectives(jaxpr)
    assert colls, "no collectives found — jaxpr walk or shard_map changed?"
    return colls


def _moved_bytes_per_chip(colls, tp: int) -> int:
    """Ring all_gather of per-shard payload b over S chips: every chip
    sends (and receives) (S-1)*b — the same accounting comm_stats uses."""
    total = 0
    for name, aval, mult in colls:
        assert name.startswith("all_gather"), \
            f"unmodeled collective {name} in the tp forward"
        shard_bytes = int(np.prod(aval.shape)) * aval.dtype.itemsize
        total += (tp - 1) * shard_bytes * mult
    return total


_SPECS = {
    "7b": llama2_7b_spec,
    "13b": llama2_13b_spec,
    "70b": llama2_70b_spec,
}


@pytest.mark.parametrize("name", sorted(_SPECS))
@pytest.mark.parametrize("wire", ["f32", "q80"])
def test_traced_collectives_match_analytic_model(name, wire):
    """The traced program's collective count and payload bytes equal the
    analytic model's, for the real model specs in both buffer modes."""
    spec = _SPECS[name]()
    if wire == "q80":
        import dataclasses

        spec = dataclasses.replace(spec,
                                   buffer_float_type=FloatType.Q80)
    tp = 8
    colls = _trace_collectives(spec, tp)

    # count: 4 per-layer gathers + the logits gather (shard_sim's n_coll)
    n_coll = sum(m for _, _, m in colls)
    assert n_coll == spec.n_layers * 4 + 1

    # payload: per-chip moved bytes == comm_stats (the bench/runtime model)
    want = ici_all_gather_bytes(spec, tp).sent_bytes
    got = _moved_bytes_per_chip(colls, tp)
    assert got == want, (got, want)

    # the Q80 wire really packs each cut into ONE u8 gather (the count —
    # whose latency term dominates the ICI budget 13:1 — must not double)
    if wire == "q80":
        layer_colls = [c for c in colls if c[2] == spec.n_layers]
        assert len(layer_colls) == 4
        assert all(a.dtype == jnp.uint8 for _, a, _ in layer_colls), \
            [a.dtype for _, a, _ in layer_colls]
        # and each payload is the Q80 wire size of its cut
        dims = sorted(int(np.prod(a.shape)) for _, a, _ in layer_colls)
        want_dims = sorted([batch_bytes(FloatType.Q80, spec.dim // tp)] * 3
                           + [batch_bytes(FloatType.Q80,
                                          spec.hidden_dim // tp)])
        assert dims == want_dims


def test_70b_headline_budget_literals():
    """The numbers the 70B projection publishes (BASELINE.md): 321
    collectives moving ~14,669 kB per chip per token with f32 buffers,
    cut ~3.8x by the Q80 wire. Derived here from the traced program, not
    from comm_stats."""
    import dataclasses

    colls = _trace_collectives(llama2_70b_spec(), 8)
    assert sum(m for _, _, m in colls) == 321
    kb = _moved_bytes_per_chip(colls, 8) / 1024
    assert abs(kb - 14669) < 1.0, kb

    spec80 = dataclasses.replace(llama2_70b_spec(),
                                 buffer_float_type=FloatType.Q80)
    kb80 = _moved_bytes_per_chip(_trace_collectives(spec80, 8), 8) / 1024
    # ~3.76x on the per-layer cuts, diluted slightly by the always-f32
    # logits gather
    assert 3.6 < kb / kb80 < 3.9, (kb, kb80)


def test_compiled_hlo_keeps_the_gathers():
    """XLA must not merge, split, or eliminate the shard_map gathers: the
    optimized module for the small spec contains exactly 4 all-gather
    instructions in the layer loop + 1 for the logits."""
    spec = small_bench_spec()
    tp = 4  # the small spec has 4 heads
    mesh = make_mesh(tp=tp)
    fwd = make_sharded_forward(spec, mesh)
    params = _abstract_params(spec)
    cache = jax.eval_shape(lambda: init_cache(spec, jnp.float32))
    tokens = jax.ShapeDtypeStruct((1,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    txt = fwd.lower(params, cache, tokens, pos).compile().as_text()
    n = txt.count(" all-gather(") + txt.count(" all-gather-start(")
    assert n == 5, f"expected 4 loop + 1 logits all-gathers, found {n}"
