"""Pin the analytic ICI model to the compiled program (VERDICT r4 #4).

Every multi-chip performance number in this repo carries an ICI term built
from `comm_stats.tp_collective_budget` (per-scheme counts AND payload).
Until now those were asserted only by the same arithmetic that produced
them. These tests derive BOTH numbers independently from the program
itself, for BOTH tp schemes (ref and fused — parallel/tp.py):

  * jaxpr level — trace `make_sharded_forward` for the REAL 7B/13B/70B
    specs (abstract params; nothing is materialized) on the virtual
    8-device mesh, walk the equation graph with scan-length multiplicity,
    and count every collective primitive with its per-shard payload aval.
  * compiled level — lower + compile the small program on the CPU mesh and
    count the all-gather / all-reduce instructions XLA actually emitted.

If the traced program ever gains/loses a collective, changes a payload
dtype (e.g. the Q80 wire packing), or the analytic model drifts from what
the program does, these fail. Anchors: the projection model feeds the 70B
north-star claim vs README.md:48; the reference's own published
transfer-per-token tables are README.md:58-69.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_llama_tpu.io.loader import Q40Weight
from distributed_llama_tpu.models.llama import init_cache
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import (_build_tree, llama2_7b_spec,
                                                llama2_13b_spec,
                                                llama2_70b_spec,
                                                small_bench_spec)
from distributed_llama_tpu.ops.quants import FloatType, batch_bytes
from distributed_llama_tpu.parallel import make_mesh, make_sharded_forward
from distributed_llama_tpu.parallel.comm_stats import (ici_all_gather_bytes,
                                                       tp_collective_budget)


def _abstract_params(spec: TransformerSpec):
    """The full-size param tree as avals only — 70B traces in seconds and
    materializes nothing."""
    def t(*shape):
        return jnp.zeros(shape, jnp.float32)

    def mm(*shape):
        *lead, d, n = shape
        return Q40Weight(jnp.zeros((*lead, d, n // 32, 16), jnp.uint8),
                         jnp.zeros((*lead, d, n // 32), jnp.float16))

    return jax.eval_shape(lambda: _build_tree(spec, t, mm))


def _collect_collectives(jaxpr, mult=1):
    """[(primitive_name, per_shard_aval, multiplicity)] for every
    collective eqn, weighting eqns inside scan bodies by trip count (the
    layer loop appears ONCE in the jaxpr but runs n_layers times)."""
    out = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        m = mult
        if name == "scan":
            m = mult * eqn.params["length"]
        if name.startswith(("all_gather", "all_to_all", "psum", "pmax",
                            "pmin", "ppermute", "reduce_scatter")):
            out.append((name, eqn.invars[0].aval, mult))
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", None)
            if hasattr(v, "eqns"):
                out.extend(_collect_collectives(v, m))
            elif inner is not None and hasattr(inner, "eqns"):
                out.extend(_collect_collectives(inner, m))
    return out


def _trace_collectives(spec: TransformerSpec, tp: int, scheme: str):
    mesh = make_mesh(tp=tp)
    fwd = make_sharded_forward(spec, mesh, scheme=scheme)
    params = _abstract_params(spec)
    cache = jax.eval_shape(lambda: init_cache(spec, jnp.float32))
    tokens = jax.ShapeDtypeStruct((1,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    jaxpr = jax.make_jaxpr(fwd)(params, cache, tokens, pos).jaxpr
    colls = _collect_collectives(jaxpr)
    assert colls, "no collectives found — jaxpr walk or shard_map changed?"
    return colls


def _moved_bytes_per_chip(colls, tp: int) -> int:
    """Ring accounting per collective kind — the same model comm_stats and
    the J001 contract use (jaxpr_contracts._moved_bytes)."""
    from distributed_llama_tpu.analysis.jaxpr_contracts import (
        _collective_kind, _moved_bytes)

    return sum(_moved_bytes(_collective_kind(name), aval, tp) * mult
               for name, aval, mult in colls)


_SPECS = {
    "7b": llama2_7b_spec,
    "13b": llama2_13b_spec,
    "70b": llama2_70b_spec,
}


# per (scheme, wire): expected collectives per layer (+1 logits gather) at
# tp=8 — the overlap scheme's ring decomposition is tp-dependent:
# 2*(tp-1) ppermutes + 2 gathers per layer (ISSUE 10)
_PER_LAYER = {("ref", "f32"): 4, ("ref", "q80"): 4,
              ("fused", "f32"): 2, ("fused", "q80"): 4,
              ("overlap", "f32"): 2 * 7 + 2, ("overlap", "q80"): 2 * 7 + 2}


@pytest.mark.parametrize("name", sorted(_SPECS))
@pytest.mark.parametrize("wire", ["f32", "q80"])
@pytest.mark.parametrize("scheme", ["ref", "fused", "overlap"])
def test_traced_collectives_match_analytic_model(name, wire, scheme):
    """The traced program's collective count and payload bytes equal the
    analytic model's, for the real model specs in both buffer modes and
    all three schemes. The fused/f32 row is the ISSUE 3 acceptance bar:
    <= 2 collectives per layer, jaxpr-verified at model scale; the
    overlap rows pin the ring decomposition (ISSUE 10: per layer,
    2*(tp-1) single-hop ppermutes + 2 band gathers)."""
    spec = _SPECS[name]()
    if wire == "q80":
        import dataclasses

        spec = dataclasses.replace(spec,
                                   buffer_float_type=FloatType.Q80)
    tp = 8
    colls = _trace_collectives(spec, tp, scheme)

    n_coll = sum(m for _, _, m in colls)
    assert n_coll == spec.n_layers * _PER_LAYER[(scheme, wire)] + 1
    assert n_coll == tp_collective_budget(spec, tp, scheme).n_collectives

    # payload: per-chip moved bytes == comm_stats (the bench/runtime model)
    want = ici_all_gather_bytes(spec, tp, scheme).sent_bytes
    got = _moved_bytes_per_chip(colls, tp)
    assert got == want, (got, want)

    if wire == "q80" and scheme == "ref":
        # the Q80 wire really packs each cut into ONE u8 gather (the count
        # — whose latency term dominates the ICI budget 13:1 — must not
        # double)
        layer_colls = [c for c in colls if c[2] == spec.n_layers]
        assert len(layer_colls) == 4
        assert all(a.dtype == jnp.uint8 for _, a, _ in layer_colls), \
            [a.dtype for _, a, _ in layer_colls]
        # and each payload is the Q80 wire size of its cut
        dims = sorted(int(np.prod(a.shape)) for _, a, _ in layer_colls)
        want_dims = sorted([batch_bytes(FloatType.Q80, spec.dim // tp)] * 3
                           + [batch_bytes(FloatType.Q80,
                                          spec.hidden_dim // tp)])
        assert dims == want_dims
    if wire == "q80" and scheme == "fused":
        # scatter+gather pairs: the gather halves carry the packed Q80
        # payload of the dim/tp shard; the scatter halves are f32
        layer_colls = [c for c in colls if c[2] == spec.n_layers]
        kinds = sorted((n.split("[")[0], str(a.dtype))
                       for n, a, _ in layer_colls)
        assert [k for k, _ in kinds].count("reduce_scatter") == 2
        ag = [(n, a) for n, a, _ in layer_colls
              if n.startswith("all_gather")]
        assert len(ag) == 2
        assert all(a.dtype == jnp.uint8 for _, a in ag)
        assert all(int(np.prod(a.shape)) ==
                   batch_bytes(FloatType.Q80, spec.dim // tp)
                   for _, a in ag)
    if wire == "f32" and scheme == "fused":
        # the acceptance shape: 2 full-dim f32 psums per layer, nothing else
        layer_colls = [c for c in colls if c[2] == spec.n_layers]
        assert len(layer_colls) == 2
        assert all(n.startswith("psum") for n, _, _ in layer_colls)
        assert all(int(np.prod(a.shape)) == spec.dim
                   for _, a, _ in layer_colls)
    if scheme == "overlap":
        # the ring decomposition: per layer 2*(tp-1) ppermutes each
        # moving one f32 dim/tp chunk (partial sums never ride the wire
        # quantized), and 2 band gathers — packed-Q80 uint8 under the
        # Q80 wire, f32 under f32 buffers
        layer_colls = [c for c in colls if c[2] == spec.n_layers]
        pp = [(n, a) for n, a, _ in layer_colls if n.startswith("ppermute")]
        ag = [(n, a) for n, a, _ in layer_colls
              if n.startswith("all_gather")]
        assert len(pp) == 2 * (tp - 1) and len(ag) == 2
        assert all(a.dtype == jnp.float32
                   and int(np.prod(a.shape)) == spec.dim // tp
                   for _, a in pp)
        if wire == "q80":
            assert all(a.dtype == jnp.uint8 for _, a in ag)
            assert all(int(np.prod(a.shape)) ==
                       batch_bytes(FloatType.Q80, spec.dim // tp)
                       for _, a in ag)
        else:
            assert all(a.dtype == jnp.float32
                       and int(np.prod(a.shape)) == spec.dim // tp
                       for _, a in ag)


def test_70b_headline_budget_literals():
    """The numbers the 70B projection publishes (BASELINE.md): ref scheme
    321 collectives moving ~14,669 kB per chip per token with f32 buffers,
    cut ~3.8x by the Q80 wire; fused scheme 161 collectives (~9,070 kB
    f32). Derived here from the traced program, not from comm_stats."""
    import dataclasses

    colls = _trace_collectives(llama2_70b_spec(), 8, "ref")
    assert sum(m for _, _, m in colls) == 321
    kb = _moved_bytes_per_chip(colls, 8) / 1024
    assert abs(kb - 14669) < 1.0, kb

    spec80 = dataclasses.replace(llama2_70b_spec(),
                                 buffer_float_type=FloatType.Q80)
    kb80 = _moved_bytes_per_chip(_trace_collectives(spec80, 8, "ref"),
                                 8) / 1024
    # ~3.76x on the per-layer cuts, diluted slightly by the always-f32
    # logits gather
    assert 3.6 < kb / kb80 < 3.9, (kb, kb80)

    fused = _trace_collectives(llama2_70b_spec(), 8, "fused")
    assert sum(m for _, _, m in fused) == 161  # HALF the launches + logits
    kbf = _moved_bytes_per_chip(fused, 8) / 1024
    assert abs(kbf - 9070) < 1.0, kbf


@pytest.mark.parametrize("scheme,want_ag,want_ar,want_cp", [
    ("ref", 5, 0, 0),      # 4 loop + 1 logits all-gathers
    ("fused", 1, 2, 0),    # 2 loop all-reduces + 1 logits all-gather
    ("overlap", 3, 0, 6),  # 2 loop + 1 logits gathers, 2*(tp-1) permutes
])
def test_compiled_hlo_keeps_the_collectives(scheme, want_ag, want_ar,
                                            want_cp):
    """XLA must not merge, split, or eliminate the shard_map collectives:
    the optimized module for the small spec contains exactly the
    scheduled instructions (the layer loop body appears once). Dense f32
    abstract weights: the census is dtype-independent, and the small
    spec's hidden (22 Q40 blocks) cannot input-shard 4 ways — quantized
    fused runs need hidden/tp as a 32-multiple (real shapes all qualify;
    shard_params raises the clear error otherwise)."""
    from distributed_llama_tpu.analysis.jaxpr_contracts import \
        abstract_params

    spec = small_bench_spec()
    tp = 4  # the small spec has 4 heads
    mesh = make_mesh(tp=tp)
    fwd = make_sharded_forward(spec, mesh, scheme=scheme)
    params = abstract_params(spec)
    cache = jax.eval_shape(lambda: init_cache(spec, jnp.float32))
    tokens = jax.ShapeDtypeStruct((1,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    txt = fwd.lower(params, cache, tokens, pos).compile().as_text()
    n_ag = txt.count(" all-gather(") + txt.count(" all-gather-start(")
    n_ar = txt.count(" all-reduce(") + txt.count(" all-reduce-start(")
    n_cp = (txt.count(" collective-permute(")
            + txt.count(" collective-permute-start("))
    assert (n_ag, n_ar, n_cp) == (want_ag, want_ar, want_cp), \
        (n_ag, n_ar, n_cp)
