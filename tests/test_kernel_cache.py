"""Pre-tiled kernel-layout sidecar cache (VERDICT r4 #7).

The cache must make the second real-model load an mmap (0 bytes
re-tiled) while producing a tree that is INDISTINGUISHABLE — same leaf
types, shapes, dtypes, and bytes — from the load-and-retile path, under
every layout the packer can pick (d-major, nb-major mix, codec
fallbacks). A stale or mismatched sidecar must rebuild, never feed a
wrong layout to the kernels.
"""

import os

import numpy as np
import pytest

from distributed_llama_tpu.io import kernel_cache as kc
from distributed_llama_tpu.io.loader import (Q40Kernel, Q40KernelNb,
                                             Q40Weight, write_model)
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

TINY = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=96, seq_len=32,
                       weights_float_type=FloatType.Q40)


def _model_file(tmp_path, spec=TINY, seed=7):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return rng.standard_normal(shape).astype(np.float32)

    tensors = {
        "tok_embedding": t(spec.vocab_size, spec.dim),
        "rms_att": t(spec.n_layers, spec.dim),
        "rms_ffn": t(spec.n_layers, spec.dim),
        "rms_final": t(spec.dim),
        "wcls": t(spec.vocab_size, spec.dim),
        **{name: t(spec.n_layers, *shape)
           for name, shape in spec.layer_matmul_shapes()},
    }
    path = str(tmp_path / "model.bin")
    write_model(path, spec, tensors)
    return path


def _trees_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        va, vb = a[k], b[k]
        # memmap is an ndarray subclass: compare container KIND (dense vs
        # the exact Q40 layout NamedTuple), not the concrete array class
        ka = type(va) if not isinstance(va, np.ndarray) else np.ndarray
        kb = type(vb) if not isinstance(vb, np.ndarray) else np.ndarray
        assert ka is kb, (k, type(va), type(vb))
        fa = [va] if isinstance(va, np.ndarray) else list(va)
        fb = [vb] if isinstance(vb, np.ndarray) else list(vb)
        for x, y in zip(fa, fb):
            assert x.dtype == y.dtype and x.shape == y.shape, k
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y), k)


def test_sidecar_roundtrip_bit_exact(tmp_path, monkeypatch):
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")  # force packing on CPU
    path = _model_file(tmp_path)
    spec1, fresh = kc.load_model_packed(path)
    side = kc.sidecar_path(path)
    assert os.path.exists(side)
    # the packed tree has kernel-layout leaves (that is what's cached)
    assert any(isinstance(v, (Q40Kernel, Q40KernelNb))
               for v in fresh.values())

    spec2, cached = kc.load_model_packed(path)
    assert spec2 == spec1
    _trees_equal(fresh, cached)
    # and the cached leaves are memmap views, not fresh copies
    mmapped = [f for v in cached.values()
               for f in ([v] if isinstance(v, np.ndarray) else list(v))
               if isinstance(f, np.memmap) or isinstance(f.base, np.memmap)]
    assert mmapped, "cache hit did not return memmap-backed leaves"


def test_key_mismatch_rebuilds(tmp_path, monkeypatch):
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    path = _model_file(tmp_path)
    kc.load_model_packed(path)
    side = kc.sidecar_path(path)
    assert kc.load_packed(side, kc.layout_key(path)) is not None
    assert kc.load_packed(side, "v1|other|key") is None

    # a different matvec cap changes the key -> rebuild instead of reuse
    monkeypatch.setenv("DLLAMA_MATVEC_CAP", "1536")
    assert kc.load_packed(side, kc.layout_key(path)) is None
    monkeypatch.delenv("DLLAMA_MATVEC_CAP")

    # overwriting the model .bin (same path, new contents) invalidates:
    # the key carries the source file's size+mtime
    os.utime(path, ns=(1, 1))
    assert kc.load_packed(side, kc.layout_key(path)) is None


def test_corrupt_sidecar_falls_back(tmp_path, monkeypatch):
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    path = _model_file(tmp_path)
    spec1, fresh = kc.load_model_packed(path)
    side = kc.sidecar_path(path)
    with open(side, "r+b") as fh:
        fh.write(b"garbage!")
    spec2, rebuilt = kc.load_model_packed(path)
    _trees_equal(fresh, rebuilt)
    # the rebuild rewrote a VALID sidecar
    assert kc.load_packed(side, kc.layout_key(path)) is not None


def test_disabled_modes_skip_sidecar(tmp_path, monkeypatch):
    # xla kernel mode: nothing to pre-tile, no sidecar written
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "xla")
    path = _model_file(tmp_path)
    _, tree = kc.load_model_packed(path)
    assert not os.path.exists(kc.sidecar_path(path))
    assert all(not isinstance(v, (Q40Kernel, Q40KernelNb))
               for v in tree.values())

    # pallas mode but cache opt-out: packed tree, still no sidecar
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    monkeypatch.setenv("DLLAMA_TILED_CACHE", "0")
    _, tree = kc.load_model_packed(path)
    assert not os.path.exists(kc.sidecar_path(path))
    assert any(isinstance(v, (Q40Kernel, Q40KernelNb))
               for v in tree.values())


def test_packed_tree_decodes_like_codec_tree(tmp_path, monkeypatch):
    """End-to-end: logits from the sidecar-cached tree equal the plain
    load_model tree's (the packed layouts are exact re-tilings)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import load_model
    from distributed_llama_tpu.models.llama import forward, init_cache

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    path = _model_file(tmp_path)
    spec, codec = load_model(path, weights_float_type=FloatType.Q40)
    kc.load_model_packed(path)            # writes the sidecar
    _, cached = kc.load_model_packed(path)  # mmap hit

    tok = jnp.asarray([5], jnp.int32)
    logits1, _ = forward(spec, codec, init_cache(spec), tok, jnp.int32(0))
    logits2, _ = forward(spec, {k: (jnp.asarray(v) if isinstance(v, np.ndarray)
                                    else type(v)(*map(jnp.asarray, v)))
                                for k, v in cached.items()},
                         init_cache(spec), tok, jnp.int32(0))
    np.testing.assert_allclose(np.asarray(logits1), np.asarray(logits2),
                               atol=2e-5)


def test_nb_major_force_invalidates(tmp_path, monkeypatch):
    """DLLAMA_NB_MAJOR changes the packed layout, so it must re-key the
    sidecar (a d-major sidecar served to a force run would silently
    ignore the layout request)."""
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    path = _model_file(tmp_path)
    kc.load_model_packed(path)
    side = kc.sidecar_path(path)
    assert kc.load_packed(side, kc.layout_key(path)) is not None
    monkeypatch.setenv("DLLAMA_NB_MAJOR", "force")
    assert kc.load_packed(side, kc.layout_key(path)) is None


def test_layout_key_folds_float_types(tmp_path, monkeypatch):
    """weights/buffer float types are part of the layout key: a future
    packed form for another float type cannot collide with the Q40/F32
    sidecar under the same key."""
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    path = _model_file(tmp_path)
    base = kc.layout_key(path)
    assert "|wf=Q40|" in base and "|bf=F32" in base
    # defaults are spelled out: explicit Q40/F32 == the default key
    assert kc.layout_key(path, weights_float_type=FloatType.Q40,
                         buffer_float_type=FloatType.F32) == base
    assert kc.layout_key(path, weights_float_type=FloatType.F16) != base
    assert kc.layout_key(path, buffer_float_type=FloatType.Q80) != base
    # and the written sidecar round-trips under the default key
    kc.load_model_packed(path)
    assert kc.load_packed(kc.sidecar_path(path), base) is not None


def test_build_lock_skips_concurrent_write(tmp_path, monkeypatch, capsys):
    """A held build lock makes a racing load SKIP the sidecar write (no
    duplicate GB-scale .tmp<pid> streams — ADVICE r5) while still
    returning a fully packed in-memory tree; once the lock is released
    the next load writes normally."""
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    path = _model_file(tmp_path)
    side = kc.sidecar_path(path)

    token = kc.try_build_lock(side)  # "another process" holds the lock
    assert token is not None
    assert kc.try_build_lock(side) is None  # held: second taker refused
    _, tree = kc.load_model_packed(path)
    assert not os.path.exists(side)  # write skipped
    assert any(isinstance(v, (Q40Kernel, Q40KernelNb))
               for v in tree.values())  # but the load itself is packed
    assert not [f for f in os.listdir(str(tmp_path))
                if ".kcache.tmp" in f]  # no orphan tmp sidecars

    kc.release_build_lock(token)
    kc.load_model_packed(path)
    assert os.path.exists(side)  # lock released: the write proceeds
    assert not os.path.exists(side + ".lock")  # and released its own lock


def test_build_lock_breaks_stale_holder(tmp_path, monkeypatch):
    """A lock whose holder crashed (old mtime) must not wedge sidecar
    writes forever: it is broken and re-acquired."""
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    path = _model_file(tmp_path)
    side = kc.sidecar_path(path)
    lock = side + ".lock"
    with open(lock, "w") as fh:
        fh.write("99999\n")
    os.utime(lock, (1, 1))  # ancient: way past _LOCK_STALE_S
    token = kc.try_build_lock(side)
    assert token is not None
    kc.release_build_lock(token)
    assert not os.path.exists(lock)
