"""Tensor-parallel parity: nSlices ∈ {1,2,4,8} must match the single-device
forward (the stage-4 gate of SURVEY.md §7; the reference could only validate
this on 8 physical Raspberry Pis — on a device mesh it's a unit test)."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

TINY = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=8,
                       n_kv_heads=8, vocab_size=96, seq_len=16)
# GQA variant: 8 q heads over 4 kv heads (kv_mul=2), shardable up to tp=4
GQA = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=8,
                      n_kv_heads=4, vocab_size=96, seq_len=16)


def _params(spec, seed=11, scale=0.1):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    p = {"tok_embedding": t(spec.vocab_size, spec.dim),
         "rms_final": 1 + t(spec.dim), "wcls": t(spec.vocab_size, spec.dim),
         "rms_att": 1 + t(spec.n_layers, spec.dim),
         "rms_ffn": 1 + t(spec.n_layers, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        p[name] = t(spec.n_layers, *shape)
    return p


def _reference_logits(spec, p, tokens):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache

    pj = {k: jnp.asarray(v) for k, v in p.items()}
    logits, _ = forward(spec, pj, init_cache(spec), jnp.asarray(tokens),
                        jnp.int32(0))
    return np.asarray(logits)


@pytest.mark.parametrize("tp", [1, 2, 4, 8])
def test_tp_parity(tp):
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh, make_sharded_forward,
                                                shard_cache, shard_params)

    if len(jax.devices()) < tp:
        pytest.skip("not enough devices")
    spec = TINY
    p = _params(spec)
    tokens = np.array([1, 5, 9, 2], dtype=np.int32)
    want = _reference_logits(spec, p, tokens)

    mesh = make_mesh(tp=tp)
    params = shard_params(p, mesh)
    cache = shard_cache(init_cache(spec), mesh)
    fwd = make_sharded_forward(spec, mesh)
    got, cache2 = fwd(params, cache, jnp.asarray(tokens), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=0, atol=2e-5)

    # decode continues from the prefilled cache
    got2, _ = fwd(params, cache2, jnp.asarray([3], dtype=np.int32),
                  jnp.int32(4))
    assert np.isfinite(np.asarray(got2)).all()


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_parity_gqa(tp):
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh, make_sharded_forward,
                                                shard_cache, shard_params)

    spec = GQA
    p = _params(spec, seed=23)
    tokens = np.array([7, 3], dtype=np.int32)
    want = _reference_logits(spec, p, tokens)

    mesh = make_mesh(tp=tp)
    fwd = make_sharded_forward(spec, mesh)
    got, _ = fwd(shard_params(p, mesh), shard_cache(init_cache(spec), mesh),
                 jnp.asarray(tokens), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=0, atol=2e-5)


def test_tp_q80_buffer_wire_quantization():
    """Q80 wire mode on tp=4 stays within quant tolerance of the f32 run."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh, make_sharded_forward,
                                                shard_cache, shard_params)

    base = TransformerSpec(dim=128, hidden_dim=256, n_layers=2, n_heads=4,
                           n_kv_heads=4, vocab_size=96, seq_len=16)
    spec80 = TransformerSpec(**{**base.__dict__,
                                "buffer_float_type": FloatType.Q80})
    p = _params(base)
    tokens = np.array([4, 8], dtype=np.int32)
    want = _reference_logits(base, p, tokens)

    mesh = make_mesh(tp=4)
    fwd = make_sharded_forward(spec80, mesh)
    got, _ = fwd(shard_params(p, mesh),
                 shard_cache(init_cache(spec80), mesh),
                 jnp.asarray(tokens), jnp.int32(0))
    diff = np.abs(np.asarray(got) - want).max()
    assert 0 < diff < 0.15  # Q80 rounding compounds over layers/sync points


def test_fused_rejects_unsplittable_q40_blocks():
    """A Q40 wo/w2 whose input dim cannot split into whole 32-blocks per
    shard must fail shard_params with the clear constraint, not a
    shard_map axis-divisibility traceback mid-placement."""
    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.parallel import make_mesh, shard_params

    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=1, n_heads=2,
                           n_kv_heads=2, vocab_size=64, seq_len=8)
    p = synth_params(spec, q40=True, seed=5)  # hidden 160 = 5 blocks
    with pytest.raises(ValueError, match="32-multiple"):
        shard_params(p, make_mesh(tp=2), scheme="fused")
    assert shard_params(p, make_mesh(tp=2), scheme="ref")  # ref: fine


def test_tp_rejects_indivisible():
    from distributed_llama_tpu.parallel import make_mesh, make_sharded_forward

    bad = TransformerSpec(dim=64, hidden_dim=150, n_layers=1, n_heads=8,
                          n_kv_heads=8, vocab_size=96, seq_len=16)
    mesh = make_mesh(tp=4)
    with pytest.raises(ValueError, match="hidden_dim"):
        make_sharded_forward(bad, mesh)


def test_engine_rejects_indivisible_before_device_put():
    """tp > n_kv_heads must fail with the clear divisibility error, not a
    device_put sharding traceback mid-load (Engine validates first)."""
    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.runtime.generate import Engine

    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=1, n_heads=4,
                           n_kv_heads=2, vocab_size=96, seq_len=16)
    p = _params(spec)
    mesh = make_mesh(tp=4)
    with pytest.raises(ValueError, match="n_kv_heads"):
        Engine(spec, p, mesh=mesh)


def _collective_census(fn, *args):
    """X-ray what the collectives actually carry: sorted (kind, dtype)
    pairs, one per collective EQN (the scan body holds the per-layer
    program once). Shared walker: analysis/jaxpr_contracts.py."""
    from distributed_llama_tpu.analysis.jaxpr_contracts import (
        _collective_kind, walk_fn_eqns)

    kinds = ("all_gather", "reduce_scatter", "psum", "all_to_all",
             "ppermute", "pmax", "pmin")
    return sorted(
        (_collective_kind(e.primitive.name), str(e.invars[0].aval.dtype))
        for e in walk_fn_eqns(fn, *args)
        if e.primitive.name.startswith(kinds))


# the small census spec (Q80 needs dim/tp and hidden/tp as 32-multiples)
_WIRE = TransformerSpec(dim=128, hidden_dim=256, n_layers=2, n_heads=4,
                        n_kv_heads=4, vocab_size=96, seq_len=16)
_WIRE80 = TransformerSpec(**{**_WIRE.__dict__,
                             "buffer_float_type": FloatType.Q80})


def test_q80_wire_gathers_carry_int8_payload():
    """Under buffer_float_type=Q80 the per-layer collectives must move the
    REAL quantized payload — int8 codes + f16 deltas — not dequantized f32
    (VERDICT r1 #4: round 1 quantize-dequantized BEFORE the gather, so the
    wire carried f32 while comm_stats claimed the 4x cut). Codes and deltas
    are packed into ONE uint8 buffer of contiguous 34-byte blocks per cut
    (VERDICT r2 #4: separate code/delta gathers doubled the per-collective
    latency term that dominates the 70B ICI budget). Scheme ref: the scan
    body holds 4 uint8 gathers plus the single f32 logits gather; in f32
    buffer mode all five are f32 (the reference schedule, unchanged).
    And values must be unchanged: quantize->pack->gather->unpack->dequantize
    equals the round-1 fake-quant path bit for bit, pinned against
    single-chip Q80."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    base, spec80 = _WIRE, _WIRE80
    p = _params(base)
    tokens = np.array([4, 8], dtype=np.int32)
    mesh = make_mesh(tp=2)

    sp = shard_params(p, mesh, scheme="ref")
    sc = shard_cache(init_cache(spec80), mesh)
    fwd80 = make_sharded_forward(spec80, mesh, scheme="ref")
    toks = jnp.asarray(tokens)
    assert _collective_census(fwd80, sp, sc, toks, jnp.int32(0)) == (
        [("all_gather", "float32")] + [("all_gather", "uint8")] * 4)
    fwd32 = make_sharded_forward(base, mesh, scheme="ref")
    assert _collective_census(
        fwd32, shard_params(p, make_mesh(tp=2), scheme="ref"),
        shard_cache(init_cache(base), mesh), toks,
        jnp.int32(0)) == [("all_gather", "float32")] * 5

    # within quant tolerance of the single-chip Q80 path. Not bit-exact by
    # design: the tp program ALSO rounds the wo/w2 outputs (they cross the
    # wire — the reference's quantizeAtt/quantizeFfn2 do the same,
    # transformer-tasks.cpp:303,411) while the single-chip path has no wire
    # there; each extra cut adds <= ~amax/254 per value (Q80 round-trip
    # bound, test_quants.py), compounded over 2 layers
    pj = {k: jnp.asarray(v) for k, v in p.items()}
    want, _ = forward(spec80, pj, init_cache(spec80), toks, jnp.int32(0))
    got, _ = fwd80(sp, sc, toks, jnp.int32(0))
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < 0.15


def test_fused_scheme_collective_census():
    """The fused scheme's traced schedule: f32 buffers — 2 psums in the
    scan body (one per block) + the f32 logits gather, nothing else (the
    ≤2-collectives-per-layer acceptance bar of ISSUE 3, jaxpr-verified
    again at model scale by test_collective_pinning / J001); Q80 buffers —
    each psum decomposes into a f32 psum_scatter + a PACKED uint8 gather,
    preserving the reference's wire-quantization cut on the gather half."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    p = _params(_WIRE)
    toks = jnp.asarray([4, 8], jnp.int32)
    mesh = make_mesh(tp=2)

    fwd32 = make_sharded_forward(_WIRE, mesh, scheme="fused")
    census = _collective_census(
        fwd32, shard_params(p, mesh, scheme="fused"),
        shard_cache(init_cache(_WIRE), mesh), toks, jnp.int32(0))
    assert census == [("all_gather", "float32"),
                      ("psum", "float32"), ("psum", "float32")]

    fwd80 = make_sharded_forward(_WIRE80, mesh, scheme="fused")
    census80 = _collective_census(
        fwd80, shard_params(p, mesh, scheme="fused"),
        shard_cache(init_cache(_WIRE80), mesh), toks, jnp.int32(0))
    assert census80 == [("all_gather", "float32"),
                        ("all_gather", "uint8"), ("all_gather", "uint8"),
                        ("reduce_scatter", "float32"),
                        ("reduce_scatter", "float32")]


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_scheme_parity_ref_vs_fused(tp):
    """ref-vs-fused equivalence on the synth model (the satellite gate of
    ISSUE 3): same logits on both wire modes — f32 buffers to fp tolerance
    (the schemes differ only in summation order: band-concat-then-matmul
    vs partial-matmul-then-psum), Q80 buffers within the compounded
    quantization tolerance (the schemes place the rounding cuts at the
    same reference task boundaries but wire different tensors)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    p = _params(_WIRE, seed=31)
    toks = jnp.asarray([4, 8, 61], jnp.int32)
    mesh = make_mesh(tp=tp)
    want = _reference_logits(_WIRE, p, np.asarray(toks))

    outs = {}
    for spec in (_WIRE, _WIRE80):
        for scheme in ("ref", "fused"):
            fwd = make_sharded_forward(spec, mesh, scheme=scheme)
            got, _ = fwd(shard_params(p, mesh, scheme=scheme),
                         shard_cache(init_cache(spec), mesh), toks,
                         jnp.int32(0))
            outs[(spec.buffer_float_type, scheme)] = np.asarray(got)

    f32_ref = outs[(FloatType.F32, "ref")]
    f32_fused = outs[(FloatType.F32, "fused")]
    np.testing.assert_allclose(f32_ref, want, rtol=0, atol=2e-5)
    np.testing.assert_allclose(f32_fused, want, rtol=0, atol=2e-5)
    np.testing.assert_allclose(f32_fused, f32_ref, rtol=0, atol=2e-5)
    # Q80: both schemes within quant tolerance of each other and the f32
    # logits (the 0.15 bound of the existing Q80 gates)
    q80_ref = outs[(FloatType.Q80, "ref")]
    q80_fused = outs[(FloatType.Q80, "fused")]
    assert np.abs(q80_fused - q80_ref).max() < 0.15
    assert np.abs(q80_fused - want).max() < 0.15


def test_q80_wire_block_byte_layout():
    """The packed wire buffer is the reference's contiguous 34-byte block
    layout (quants.hpp:21-24): per 32-value block, 32 int8 codes then the 2
    f16-delta bytes — asserted on the raw uint8 buffer handed to the
    collective, and the unpack must reproduce the fake-quant values exactly
    (pack/gather/unpack is lossless)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.linear import fake_quant_q80
    from distributed_llama_tpu.ops.quants import quantize_q80_jax
    from distributed_llama_tpu.parallel import tp

    spec80 = TransformerSpec(dim=64, hidden_dim=128, n_layers=1, n_heads=2,
                             n_kv_heads=2, vocab_size=32, seq_len=8,
                             buffer_float_type=FloatType.Q80)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((1, 64)).astype(np.float32))
    captured = {}

    def tile2(a, axis):
        captured["buf"] = np.asarray(a)
        return jnp.concatenate([a, a], axis=axis)

    out = np.asarray(tp._wire_gather(spec80, x, gather_fn=tile2))

    buf = captured["buf"]
    assert buf.dtype == np.uint8 and buf.shape == (1, 2 * 34)  # nb=2 blocks
    qs, d = quantize_q80_jax(x)
    qs, d = np.asarray(qs), np.asarray(d)
    for b in range(2):
        blk = buf[0, b * 34:(b + 1) * 34]
        np.testing.assert_array_equal(blk[:32], qs[0, b].view(np.uint8))
        np.testing.assert_array_equal(blk[32:], d[0, b:b + 1]
                                      .view(np.uint8).reshape(2))
    # the gathered result = the fake-quant values, tiled in shard order
    want = np.asarray(fake_quant_q80(x))
    np.testing.assert_array_equal(out, np.concatenate([want, want], axis=-1))
