"""Tensor-parallel parity: nSlices ∈ {1,2,4,8} must match the single-device
forward (the stage-4 gate of SURVEY.md §7; the reference could only validate
this on 8 physical Raspberry Pis — on a device mesh it's a unit test)."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

TINY = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=8,
                       n_kv_heads=8, vocab_size=96, seq_len=16)
# GQA variant: 8 q heads over 4 kv heads (kv_mul=2), shardable up to tp=4
GQA = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=8,
                      n_kv_heads=4, vocab_size=96, seq_len=16)


def _params(spec, seed=11, scale=0.1):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    p = {"tok_embedding": t(spec.vocab_size, spec.dim),
         "rms_final": 1 + t(spec.dim), "wcls": t(spec.vocab_size, spec.dim),
         "rms_att": 1 + t(spec.n_layers, spec.dim),
         "rms_ffn": 1 + t(spec.n_layers, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        p[name] = t(spec.n_layers, *shape)
    return p


def _reference_logits(spec, p, tokens):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache

    pj = {k: jnp.asarray(v) for k, v in p.items()}
    logits, _ = forward(spec, pj, init_cache(spec), jnp.asarray(tokens),
                        jnp.int32(0))
    return np.asarray(logits)


@pytest.mark.parametrize("tp", [1, 2, 4, 8])
def test_tp_parity(tp):
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh, make_sharded_forward,
                                                shard_cache, shard_params)

    if len(jax.devices()) < tp:
        pytest.skip("not enough devices")
    spec = TINY
    p = _params(spec)
    tokens = np.array([1, 5, 9, 2], dtype=np.int32)
    want = _reference_logits(spec, p, tokens)

    mesh = make_mesh(tp=tp)
    params = shard_params(p, mesh)
    cache = shard_cache(init_cache(spec), mesh)
    fwd = make_sharded_forward(spec, mesh)
    got, cache2 = fwd(params, cache, jnp.asarray(tokens), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=0, atol=2e-5)

    # decode continues from the prefilled cache
    got2, _ = fwd(params, cache2, jnp.asarray([3], dtype=np.int32),
                  jnp.int32(4))
    assert np.isfinite(np.asarray(got2)).all()


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_parity_gqa(tp):
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh, make_sharded_forward,
                                                shard_cache, shard_params)

    spec = GQA
    p = _params(spec, seed=23)
    tokens = np.array([7, 3], dtype=np.int32)
    want = _reference_logits(spec, p, tokens)

    mesh = make_mesh(tp=tp)
    fwd = make_sharded_forward(spec, mesh)
    got, _ = fwd(shard_params(p, mesh), shard_cache(init_cache(spec), mesh),
                 jnp.asarray(tokens), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=0, atol=2e-5)


def test_tp_q80_buffer_wire_quantization():
    """Q80 wire mode on tp=4 stays within quant tolerance of the f32 run."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh, make_sharded_forward,
                                                shard_cache, shard_params)

    base = TransformerSpec(dim=128, hidden_dim=256, n_layers=2, n_heads=4,
                           n_kv_heads=4, vocab_size=96, seq_len=16)
    spec80 = TransformerSpec(**{**base.__dict__,
                                "buffer_float_type": FloatType.Q80})
    p = _params(base)
    tokens = np.array([4, 8], dtype=np.int32)
    want = _reference_logits(base, p, tokens)

    mesh = make_mesh(tp=4)
    fwd = make_sharded_forward(spec80, mesh)
    got, _ = fwd(shard_params(p, mesh),
                 shard_cache(init_cache(spec80), mesh),
                 jnp.asarray(tokens), jnp.int32(0))
    diff = np.abs(np.asarray(got) - want).max()
    assert 0 < diff < 0.15  # Q80 rounding compounds over layers/sync points


def test_tp_rejects_indivisible():
    from distributed_llama_tpu.parallel import make_mesh, make_sharded_forward

    bad = TransformerSpec(dim=64, hidden_dim=150, n_layers=1, n_heads=8,
                          n_kv_heads=8, vocab_size=96, seq_len=16)
    mesh = make_mesh(tp=4)
    with pytest.raises(ValueError, match="hidden_dim"):
        make_sharded_forward(bad, mesh)


def test_engine_rejects_indivisible_before_device_put():
    """tp > n_kv_heads must fail with the clear divisibility error, not a
    device_put sharding traceback mid-load (Engine validates first)."""
    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.runtime.generate import Engine

    spec = TransformerSpec(dim=64, hidden_dim=160, n_layers=1, n_heads=4,
                           n_kv_heads=2, vocab_size=96, seq_len=16)
    p = _params(spec)
    mesh = make_mesh(tp=4)
    with pytest.raises(ValueError, match="n_kv_heads"):
        Engine(spec, p, mesh=mesh)


def _all_gather_dtypes(fn, *args):
    """X-ray what the collectives actually carry (shared walker:
    analysis/jaxpr_contracts.py)."""
    from distributed_llama_tpu.analysis.jaxpr_contracts import walk_fn_eqns

    return sorted(str(e.invars[0].aval.dtype) for e in walk_fn_eqns(fn, *args)
                  if e.primitive.name == "all_gather")


def test_q80_wire_gathers_carry_int8_payload():
    """Under buffer_float_type=Q80 the per-layer collectives must move the
    REAL quantized payload — int8 codes + f16 deltas — not dequantized f32
    (VERDICT r1 #4: round 1 quantize-dequantized BEFORE the gather, so the
    wire carried f32 while comm_stats claimed the 4x cut). Codes and deltas
    are packed into ONE uint8 buffer of contiguous 34-byte blocks per cut
    (VERDICT r2 #4: separate code/delta gathers doubled the per-collective
    latency term that dominates the 70B ICI budget). The scan body holds
    the per-layer program once: expect 4 uint8 gathers there plus the
    single f32 logits gather; in f32 buffer mode all five are f32.
    And values must be unchanged: quantize->pack->gather->unpack->dequantize
    equals the round-1 fake-quant path bit for bit, pinned against
    single-chip Q80."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    base = TransformerSpec(dim=128, hidden_dim=256, n_layers=2, n_heads=4,
                           n_kv_heads=4, vocab_size=96, seq_len=16)
    spec80 = TransformerSpec(**{**base.__dict__,
                                "buffer_float_type": FloatType.Q80})
    p = _params(base)
    tokens = np.array([4, 8], dtype=np.int32)
    mesh = make_mesh(tp=2)

    sp = shard_params(p, mesh)
    sc = shard_cache(init_cache(spec80), mesh)
    fwd80 = make_sharded_forward(spec80, mesh)
    toks = jnp.asarray(tokens)
    assert _all_gather_dtypes(fwd80, sp, sc, toks, jnp.int32(0)) == (
        ["float32"] + ["uint8"] * 4)
    fwd32 = make_sharded_forward(base, mesh)
    assert _all_gather_dtypes(
        fwd32, shard_params(p, make_mesh(tp=2)),
        shard_cache(init_cache(base), mesh), toks,
        jnp.int32(0)) == ["float32"] * 5

    # within quant tolerance of the single-chip Q80 path. Not bit-exact by
    # design: the tp program ALSO rounds the wo/w2 outputs (they cross the
    # wire — the reference's quantizeAtt/quantizeFfn2 do the same,
    # transformer-tasks.cpp:303,411) while the single-chip path has no wire
    # there; each extra cut adds <= ~amax/254 per value (Q80 round-trip
    # bound, test_quants.py), compounded over 2 layers
    pj = {k: jnp.asarray(v) for k, v in p.items()}
    want, _ = forward(spec80, pj, init_cache(spec80), toks, jnp.int32(0))
    got, _ = fwd80(sp, sc, toks, jnp.int32(0))
    assert np.abs(np.asarray(got) - np.asarray(want)).max() < 0.15


def test_q80_wire_block_byte_layout():
    """The packed wire buffer is the reference's contiguous 34-byte block
    layout (quants.hpp:21-24): per 32-value block, 32 int8 codes then the 2
    f16-delta bytes — asserted on the raw uint8 buffer handed to the
    collective, and the unpack must reproduce the fake-quant values exactly
    (pack/gather/unpack is lossless)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.linear import fake_quant_q80
    from distributed_llama_tpu.ops.quants import quantize_q80_jax
    from distributed_llama_tpu.parallel import tp

    spec80 = TransformerSpec(dim=64, hidden_dim=128, n_layers=1, n_heads=2,
                             n_kv_heads=2, vocab_size=32, seq_len=8,
                             buffer_float_type=FloatType.Q80)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((1, 64)).astype(np.float32))
    captured = {}

    def tile2(a, axis):
        captured["buf"] = np.asarray(a)
        return jnp.concatenate([a, a], axis=axis)

    out = np.asarray(tp._wire_gather(spec80, x, gather_fn=tile2))

    buf = captured["buf"]
    assert buf.dtype == np.uint8 and buf.shape == (1, 2 * 34)  # nb=2 blocks
    qs, d = quantize_q80_jax(x)
    qs, d = np.asarray(qs), np.asarray(d)
    for b in range(2):
        blk = buf[0, b * 34:(b + 1) * 34]
        np.testing.assert_array_equal(blk[:32], qs[0, b].view(np.uint8))
        np.testing.assert_array_equal(blk[32:], d[0, b:b + 1]
                                      .view(np.uint8).reshape(2))
    # the gathered result = the fake-quant values, tiled in shard order
    want = np.asarray(fake_quant_q80(x))
    np.testing.assert_array_equal(out, np.concatenate([want, want], axis=-1))
