"""Batch + tensor parallelism: the tp-sharded lockstep batch decode step must
match the single-chip batch path (tokens exactly at temp=0, logits to fp
tolerance) — the stage-4 parity gate of SURVEY.md §7 extended to batch."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.parallel import make_mesh

# GQA (kv_mul=2) with 4 kv heads so tp=4 genuinely shards and runs
SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=8,
                       n_kv_heads=4, vocab_size=128, seq_len=16)


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


@pytest.mark.parametrize("tp", [2, 4])
def test_batch_tp_step_matches_single_chip(params, tp):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward_batch,
                                                    init_cache_batch,
                                                    params_to_device)
    from distributed_llama_tpu.parallel import (make_sharded_forward_batch,
                                                shard_cache_batch,
                                                shard_params)

    B = 3
    tokens0 = jnp.asarray([7, 17, 40], dtype=jnp.int32)
    tokens1 = jnp.asarray([5, 9, 77], dtype=jnp.int32)

    dev = params_to_device(params)
    lg_ref = []
    c = init_cache_batch(SPEC, B)
    for pos, toks in enumerate((tokens0, tokens1)):
        lg, c = forward_batch(SPEC, dev, c, toks, jnp.int32(pos))
        lg_ref.append(np.asarray(lg))
    cache_ref = c

    mesh = make_mesh(tp=tp)
    sharded = shard_params(params, mesh)
    c = shard_cache_batch(init_cache_batch(SPEC, B), mesh)
    step = make_sharded_forward_batch(SPEC, mesh)
    for pos, toks in enumerate((tokens0, tokens1)):
        lg, c = step(sharded, c, toks, jnp.int32(pos))
        np.testing.assert_allclose(np.asarray(lg), lg_ref[pos],
                                   rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(c.k), np.asarray(cache_ref.k),
                               rtol=1e-5, atol=1e-5)


def test_batch_tp_decode_loop_matches_single_chip(params):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (init_cache_batch,
                                                    params_to_device)
    from distributed_llama_tpu.parallel import (make_sharded_forward_batch,
                                                shard_cache_batch,
                                                shard_params)
    from distributed_llama_tpu.runtime.decode import make_batch_decode_loop

    steps, B = 8, 2
    prompts = [[1, 5, 9], [1, 22]]  # ragged: row 1 samples earlier
    padded = np.full((B, steps + 1), -1, dtype=np.int32)
    for b, p in enumerate(prompts):
        padded[b, :len(p)] = p
    first = jnp.asarray([p[0] for p in prompts], jnp.int32)
    coins = jnp.zeros((B, steps), jnp.float32)

    dev = params_to_device(params)
    run1 = make_batch_decode_loop(SPEC, steps, temperature=0.0, topp=0.9)
    toks_ref, _ = run1(dev, init_cache_batch(SPEC, B), jnp.asarray(padded),
                       first, coins)

    mesh = make_mesh(tp=2)
    sharded = shard_params(params, mesh)
    step = make_sharded_forward_batch(SPEC, mesh)
    run2 = make_batch_decode_loop(SPEC, steps, temperature=0.0, topp=0.9,
                                  step_fn=step)
    toks_tp, _ = run2(sharded, shard_cache_batch(init_cache_batch(SPEC, B),
                                                 mesh),
                      jnp.asarray(padded), first, coins)
    np.testing.assert_array_equal(np.asarray(toks_tp), np.asarray(toks_ref))


@pytest.mark.parametrize("sp,tp", [(2, 1), (4, 1), (2, 2)])
def test_batch_sp_step_matches_single_chip(params, sp, tp):
    """sp-sharded batch decode (per-row vmapped ring-cache attention):
    logits and written cache chunks match the single-chip batch path."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward_batch,
                                                    init_cache_batch,
                                                    params_to_device)
    from distributed_llama_tpu.parallel import (make_sharded_forward_batch,
                                                shard_cache_batch,
                                                shard_params)

    B = 3
    tokens0 = jnp.asarray([7, 17, 40], dtype=jnp.int32)
    tokens1 = jnp.asarray([5, 9, 77], dtype=jnp.int32)

    dev = params_to_device(params)
    lg_ref = []
    c = init_cache_batch(SPEC, B)
    for pos, toks in enumerate((tokens0, tokens1)):
        lg, c = forward_batch(SPEC, dev, c, toks, jnp.int32(pos))
        lg_ref.append(np.asarray(lg))

    mesh = make_mesh(sp=sp, tp=tp)
    sharded = shard_params(params, mesh)
    cb = shard_cache_batch(init_cache_batch(SPEC, B), mesh)
    step = make_sharded_forward_batch(SPEC, mesh)
    for pos, toks in enumerate((tokens0, tokens1)):
        lg, cb = step(sharded, cb, toks, jnp.int32(pos))
        np.testing.assert_allclose(np.asarray(lg), lg_ref[pos],
                                   rtol=2e-5, atol=2e-5)
    # the written cache prefix (positions 0..1) matches the single-chip
    # cache — the sp-chunked writes landed in the right global slots
    np.testing.assert_allclose(np.asarray(cb.k[:, :, :2]),
                               np.asarray(c.k[:, :, :2]),
                               rtol=1e-5, atol=1e-5)

    # ragged per-row clocks through the same sp program, vs the single-chip
    # ragged step on the same cache state
    rag_toks = jnp.asarray([3, 4, 5], jnp.int32)
    rag_pos = jnp.asarray([2, 0, 1], jnp.int32)
    from distributed_llama_tpu.models.llama import forward_batch_ragged

    lg_want, _ = forward_batch_ragged(SPEC, dev, c, rag_toks, rag_pos)
    lg, cb = step(sharded, cb, rag_toks, rag_pos)
    np.testing.assert_allclose(np.asarray(lg), np.asarray(lg_want),
                               rtol=2e-5, atol=2e-5)


def test_batch_tp_rejects_indivisible(params):
    from distributed_llama_tpu.parallel import make_sharded_forward_batch

    with pytest.raises(ValueError, match="n_kv_heads"):
        make_sharded_forward_batch(SPEC, make_mesh(tp=8))
