"""Crash recovery (ISSUE 9): ContinuousEngine.recover must replay
journaled requests BITWISE — the continued stream equals the
uninterrupted run's, greedy trivially and sampled via coin-cursor replay
— across cache layouts (contiguous, paged, speculative), double crashes,
and the graceful-drain suspend path."""

import os

import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.obs.metrics import Registry
from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                      Request)
from distributed_llama_tpu.runtime.journal import (RequestJournal,
                                                   load_journal)

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=32)


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


def _make(params, journal=None, **overrides):
    kw = dict(slots=2, temperature=0.8, topp=0.9, seed=11,
              metrics=Registry(), prefill_chunk=4, page_size=4,
              kv_pages=24)
    kw.update(overrides)
    return ContinuousEngine(SPEC, params, journal=journal, **kw)


def _reqs():
    """One greedy, one seeded-sampled — both must replay bitwise."""
    return [Request(tokens=[1, 9, 17, 25], steps=24, temperature=0.0,
                    topp=0.9, seed=501),
            Request(tokens=[1, 9, 17, 42], steps=24, temperature=0.9,
                    topp=0.9, seed=502)]


def _drain(eng):
    while eng.step_many(eng.block_steps, quiet=True):
        pass


def _reference(params, **overrides):
    eng = _make(params, **overrides)
    reqs = _reqs()
    for r in reqs:
        eng.submit(r)
    _drain(eng)
    return [r.out for r in reqs]


def _interrupted(params, path, n_iters=9, **overrides):
    """Simulated SIGKILL: journal + engine, step a few times, abandon the
    process state (no close, no retire) — only the journal survives."""
    journal = RequestJournal(path)
    eng = _make(params, journal=journal, **overrides)
    reqs = _reqs()
    for r in reqs:
        eng.submit(r)
    for _ in range(n_iters):
        eng.step_many(eng.block_steps, quiet=True)
    assert all(not r.done.is_set() for r in reqs), \
        "interrupt point too late: nothing left to recover"
    assert all(r.n_sampled >= 2 for r in reqs)
    return journal


def _recover_and_finish(params, path, **overrides):
    journal = RequestJournal(path)
    eng = _make(params, journal=journal, **overrides)
    n = eng.recover()
    with eng._lock:
        recovered = list(eng._queue)
    _drain(eng)
    return eng, n, [r.out for r in recovered]


@pytest.mark.parametrize("layout", ["paged", "contiguous", "speculative"])
def test_recovered_streams_bitwise_identical(params, tmp_path, layout):
    overrides = {"paged": {},
                 "contiguous": {"page_size": 0, "kv_pages": 0},
                 "speculative": {"spec_k": 3}}[layout]
    ref = _reference(params, **overrides)
    path = str(tmp_path / "j.journal")
    _interrupted(params, path, **overrides)
    eng, n, outs = _recover_and_finish(params, path, **overrides)
    assert n == 2
    assert outs[0] == ref[0]  # greedy
    assert outs[1] == ref[1]  # seeded-sampled: coin-cursor replay
    assert eng.audit_pages() == []
    if eng._obs is not None:
        assert eng._obs.recoveries.value == 2


def test_double_crash_replays_exactly_one_life_per_request(params,
                                                          tmp_path):
    """Crash, recover, crash AGAIN mid-replay, recover: every recovery
    closes the previous life with a `recovered` retire and re-admits one
    fresh entry, so the third process still sees exactly two live
    requests and still converges on the reference streams."""
    ref = _reference(params)
    path = str(tmp_path / "j.journal")
    _interrupted(params, path)
    # second life: recover, then die mid-replay
    j2 = RequestJournal(path)
    eng2 = _make(params, journal=j2)
    assert eng2.recover() == 2
    for _ in range(3):
        eng2.step_many(eng2.block_steps, quiet=True)
    # third life: exactly two live entries (old lives retired 'recovered')
    j3 = RequestJournal(path)
    assert len(j3.incomplete()) == 2
    eng3 = _make(params, journal=j3)
    assert eng3.recover() == 2
    with eng3._lock:
        recovered = list(eng3._queue)
    _drain(eng3)
    assert [r.out for r in recovered] == ref
    assert eng3.audit_pages() == []


def test_crash_immediately_after_recover_leaves_no_duplicates(params,
                                                              tmp_path):
    """Die the instant recover() returns — before a single step or a
    clean close: the recovers-carrying admits already closed the old
    lives, so the next process sees exactly one live entry per request
    (not a duplicate pair per request)."""
    ref = _reference(params)
    path = str(tmp_path / "j.journal")
    _interrupted(params, path)
    j2 = RequestJournal(path)
    eng2 = _make(params, journal=j2)
    assert eng2.recover() == 2  # and "crash": no steps, no close
    j3 = RequestJournal(path)
    assert len(j3.incomplete()) == 2
    eng3 = _make(params, journal=j3)
    assert eng3.recover() == 2
    with eng3._lock:
        recovered = list(eng3._queue)
    _drain(eng3)
    assert [r.out for r in recovered] == ref
    assert eng3.audit_pages() == []


def test_suspend_journals_remainder_for_recovery(params, tmp_path):
    """The graceful-drain wrap-up: suspend() wakes waiters with an error
    but writes NO retirement — the journal carries the work to the next
    process, which continues bitwise."""
    ref = _reference(params)
    path = str(tmp_path / "j.journal")
    journal = RequestJournal(path)
    eng = _make(params, journal=journal)
    reqs = _reqs()
    for r in reqs:
        eng.submit(r)
    for _ in range(6):
        eng.step_many(eng.block_steps, quiet=True)
    n = eng.suspend()
    assert n == 2
    assert all(r.done.is_set() and r.error is not None for r in reqs)
    assert eng.audit_pages() == []
    journal.close()
    assert len([e for e in load_journal(path) if e.status is None]) == 2
    _, n2, outs = _recover_and_finish(params, path)
    assert n2 == 2 and outs == ref


def test_suspend_without_journal_refuses(params):
    eng = _make(params)
    with pytest.raises(ValueError, match="journal"):
        eng.suspend()
    with pytest.raises(ValueError, match="journal"):
        eng.recover()


def test_post_recovery_ids_do_not_alias(params, tmp_path):
    """A recovered engine numbers new requests past every journaled id —
    new records must never alias an old request's history."""
    path = str(tmp_path / "j.journal")
    _interrupted(params, path)
    journal = RequestJournal(path)
    eng = _make(params, journal=journal)
    eng.recover()
    extra = Request(tokens=[1, 3, 5], steps=6, temperature=0.0)
    eng.submit(extra)
    _drain(eng)
    journal.close()
    rids = [e.rid for e in load_journal(path)]
    assert len(rids) == len(set(rids))
    assert extra.index == max(rids)
    assert extra.out  # the fresh request actually ran


def test_recovery_rides_prefix_tree(params, tmp_path):
    """Recovered prompts re-derive their KV through admission prefill and
    the radix tree — the first recovered sibling publishes its prefix,
    later ones share it (the property that makes recovery cheap)."""
    path = str(tmp_path / "j.journal")
    journal = RequestJournal(path)
    eng = _make(params, journal=journal, slots=4)
    shared = [1, 7, 7, 7, 7, 7, 7, 7, 7]  # two full pages of prefix
    reqs = [Request(tokens=shared + [20 + i], steps=20, temperature=0.0)
            for i in range(3)]
    for r in reqs:
        eng.submit(r)
    for _ in range(5):
        eng.step_many(eng.block_steps, quiet=True)
    journal2 = RequestJournal(path)
    eng2 = _make(params, journal=journal2, slots=4)
    assert eng2.recover() == 3
    _drain(eng2)
    # the recovered siblings shared prompt pages through the tree
    assert eng2.allocator.prefix_hits >= 1
    assert eng2.audit_pages() == []


# ---------------------------------------- config fingerprint guard (PR 10)


def _fingerprint(seed_policy="explicit:11", scheme="single", **over):
    import dataclasses

    from distributed_llama_tpu.runtime.journal import config_fingerprint

    spec = dataclasses.replace(SPEC, **{k: v for k, v in over.items()
                                        if hasattr(SPEC, k)}) \
        if over else SPEC
    return config_fingerprint(spec, scheme, seed_policy,
                              weights_digest="abcd1234deadbeef")


def test_recover_matching_config_proceeds(params, tmp_path):
    """The WAL header records the serving-config fingerprint; a restart
    under the SAME config recovers normally."""
    path = str(tmp_path / "j")
    j = RequestJournal(path, config=_fingerprint())
    eng = _make(params, journal=j)
    eng.submit(_reqs()[0])
    eng.step_many(1, quiet=True)
    # simulated crash; same config on restart
    j2 = RequestJournal(path, config=_fingerprint())
    assert j2.header_config == _fingerprint()
    eng2 = _make(params, journal=j2)
    assert eng2.recover() == 1
    _drain(eng2)


def test_recover_refuses_mismatched_config(params, tmp_path):
    """A journal with LIVE work recorded under a different config (pinned
    seed, scheme, weight digest, dims...) must REFUSE recovery with the
    drifted keys named — no more silently-wrong bitwise replays across
    config changes."""
    from distributed_llama_tpu.runtime.journal import JournalConfigMismatch

    path = str(tmp_path / "j")
    j = RequestJournal(path, config=_fingerprint("explicit:11"))
    eng = _make(params, journal=j)
    eng.submit(_reqs()[0])
    eng.step_many(1, quiet=True)
    # restart pinned to a different seed: every NEW request's stream
    # would re-derive differently
    j2 = RequestJournal(path, config=_fingerprint("explicit:99"))
    eng2 = _make(params, journal=j2)
    with pytest.raises(JournalConfigMismatch, match="seed_policy"):
        eng2.recover()
    # a scheme change refuses too, naming the key
    j3 = RequestJournal(path, config=_fingerprint(scheme="overlap"))
    eng3 = _make(params, journal=j3)
    with pytest.raises(JournalConfigMismatch, match="tp_scheme"):
        eng3.recover()


def test_recover_refuses_kv_quant_change(params, tmp_path):
    """The ISSUE 11 fingerprint key: a KV-dtype change (f32 journal under
    q8 serving, or the reverse) flips every logit past position 0, so
    recovery refuses with ``kv_quant`` named. The key is omitted at f32,
    so pre-PR-11 journals keep recovering under f32 serving (the legacy
    compatibility contract); the full engine-level drill — live q8
    engine included — runs in tests/test_kv_quant.py."""
    from distributed_llama_tpu.runtime.journal import JournalConfigMismatch

    assert "kv_quant" not in _fingerprint()  # f32 = legacy-compatible
    path = str(tmp_path / "j")
    j = RequestJournal(path, config=_fingerprint())
    eng = _make(params, journal=j)
    eng.submit(_reqs()[0])
    eng.step_many(1, quiet=True)
    # restart with q8 KV pages: same dims, same seed policy, different
    # cache numerics — refuse, naming the key
    from distributed_llama_tpu.runtime.journal import config_fingerprint

    q8_cfg = config_fingerprint(SPEC, "single", "explicit:11",
                                weights_digest="abcd1234deadbeef",
                                kv_quant="q8")
    j2 = RequestJournal(path, config=q8_cfg)
    eng2 = _make(params, journal=j2)
    with pytest.raises(JournalConfigMismatch, match="kv_quant"):
        eng2.recover()


def test_recover_adopts_config_when_nothing_live(params, tmp_path):
    """A config change over a journal with NOTHING incomplete has nothing
    to corrupt: recover() adopts the serving config (header re-stamped)
    instead of stranding the deployment — the advertised-bitwise
    fused→overlap upgrade must not require deleting journals."""
    path = str(tmp_path / "j")
    j = RequestJournal(path, config=_fingerprint(scheme="fused"))
    eng = _make(params, journal=j)
    req = _reqs()[0]
    eng.submit(req)
    _drain(eng)
    assert req.done.is_set()
    j.close()
    # restart under a new scheme: zero live entries -> adopt, recover 0
    new_cfg = _fingerprint(scheme="overlap")
    j2 = RequestJournal(path, config=new_cfg)
    eng2 = _make(params, journal=j2)
    assert eng2.recover() == 0
    assert j2.header_config == new_cfg
    j2.close()
    # the adopted header survives reopen: the NEXT crash compares
    # against the config its requests actually ran under
    j3 = RequestJournal(path)
    assert j3.header_config == new_cfg
    j3.close()


def test_recover_legacy_header_unchecked(params, tmp_path):
    """Pre-fingerprint journals (no config in the header) recover without
    the guard — refusing every existing journal on upgrade would drop
    in-flight work the operator kept on purpose."""
    path = str(tmp_path / "j")
    j = RequestJournal(path)  # legacy: no config recorded
    eng = _make(params, journal=j)
    eng.submit(_reqs()[0])
    eng.step_many(1, quiet=True)
    j2 = RequestJournal(path, config=_fingerprint())
    assert j2.header_config is None  # the header stays legacy
    eng2 = _make(params, journal=j2)
    assert eng2.recover() == 1
    _drain(eng2)


def test_compaction_preserves_recorded_config(params, tmp_path):
    """The compaction rewrite must carry the fingerprint forward — a
    rotated journal that silently dropped its config would skip the
    guard on the next restart."""
    path = str(tmp_path / "j")
    j = RequestJournal(path, config=_fingerprint())
    j.admit(0, [1, 5], steps=4, temperature=0.0, topp=0.9, seed=100)
    j.retire(0, "done")
    j.compact()
    j.close()
    j2 = RequestJournal(path)
    assert j2.header_config == _fingerprint()
    j2.close()


# --------------------------------------------- KV tiering interop (ISSUE 12)


def test_recovery_promotes_disk_resident_prefix_bitwise(params, tmp_path):
    """A recovered request whose shared prefix pages sit on DISK promotes
    them through the same async path as live admissions — and the
    continued stream is still bitwise the uninterrupted run's. Sequence:
    serve + publish the prefix, spill it to disk, crash mid-decode,
    recover into the SAME engine state (tree with disk-tier nodes) —
    recovery's forced-token replay admission must hit the spilled
    prefix, promote it, and converge on the reference."""
    from distributed_llama_tpu.runtime.paging import TIER_DISK

    prefix = [1, 9, 17, 25, 2, 4, 6, 8]  # two full pages at ps=4
    tiered = dict(kv_pages=8, kv_disk_dir=str(tmp_path / "kv"))

    # reference: the uninterrupted run (all-HBM — tiering is invisible)
    ref_eng = _make(params, kv_pages=24)
    ref_req = Request(tokens=list(prefix) + [3], steps=24,
                      temperature=0.9, topp=0.9, seed=502)
    ref_eng.submit(ref_req)
    _drain(ref_eng)

    path = str(tmp_path / "j.journal")
    journal = RequestJournal(path)
    eng = _make(params, journal=journal, **tiered)
    # publish the prefix via a first request, then spill it to disk
    warm = Request(tokens=list(prefix) + [7], steps=24, temperature=0.0,
                   topp=0.9, seed=501)
    eng.submit(warm)
    _drain(eng)
    assert eng.allocator.demote_cold(2) == 2
    assert eng.allocator.tier_page_counts()[TIER_DISK] > 0
    # now the request that will crash mid-decode
    victim = Request(tokens=list(prefix) + [3], steps=24,
                     temperature=0.9, topp=0.9, seed=502)
    eng.submit(victim)
    for _ in range(6):
        eng.step_many(eng.block_steps, quiet=True)
    assert not victim.done.is_set() and victim.n_sampled >= 2
    # simulated SIGKILL: abandon the engine; only the journal survives.
    # The fresh process re-publishes the prefix (a sibling request),
    # spills it to disk again, THEN recovers — the recovered admission
    # must promote from disk.
    j2 = RequestJournal(path)
    eng2 = _make(params, journal=j2, **tiered)
    warm2 = Request(tokens=list(prefix) + [7], steps=24, temperature=0.0,
                    topp=0.9, seed=501)
    eng2.submit(warm2)
    _drain(eng2)
    assert eng2.allocator.demote_cold(2) == 2
    assert eng2.allocator.tier_page_counts()[TIER_DISK] > 0
    assert eng2.recover() == 1
    with eng2._lock:
        (rec,) = list(eng2._queue)
    _drain(eng2)
    assert rec.out == ref_req.out  # bitwise through the disk promotion
    assert eng2.allocator.promotions[TIER_DISK] > 0
    assert eng2.audit_pages() == []


def test_fingerprint_kv_tiers_keys_omitted_when_off(params, tmp_path):
    """ISSUE 12 satellite: the kv_tiers fingerprint keys are omitted when
    tiering is off — legacy journals keep recovering — and a tier-budget
    change under live work refuses with the key named."""
    from distributed_llama_tpu.runtime.journal import (
        JournalConfigMismatch, config_fingerprint)

    base = _fingerprint()
    assert "kv_host_pages" not in base and "kv_disk" not in base

    def tiered_cfg(host_pages):
        return config_fingerprint(SPEC, "single", "explicit:11",
                                  weights_digest="abcd1234deadbeef",
                                  kv_host_pages=host_pages, kv_disk=True)

    path = str(tmp_path / "j")
    j = RequestJournal(path, config=tiered_cfg(64))
    eng = _make(params, journal=j)
    eng.submit(_reqs()[0])
    eng.step_many(1, quiet=True)
    # restart with a different host budget: refuse, naming the key
    j2 = RequestJournal(path, config=tiered_cfg(128))
    eng2 = _make(params, journal=j2)
    with pytest.raises(JournalConfigMismatch, match="kv_host_pages"):
        eng2.recover()
    # restart under untiered serving: kv keys absent on one side -> named
    j3 = RequestJournal(path, config=_fingerprint())
    eng3 = _make(params, journal=j3)
    with pytest.raises(JournalConfigMismatch, match="kv_disk"):
        eng3.recover()
