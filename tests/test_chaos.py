"""Chaos drills + fault-injection hooks (runtime/chaos.py, ISSUE 8):
deterministic injection, post-drill invariant audits, the engine cancel()
path, and the server-level disconnect regression that counts
kv_pages_free before/after."""

import json
import urllib.error
import urllib.request

import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.obs.metrics import Registry
from distributed_llama_tpu.runtime.chaos import (ChaosMonkey, check_invariants,
                                                 run_drills, scrape_problems)
from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                      Request)
from distributed_llama_tpu.runtime.paging import PagedAllocator

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=32)


class _IdTokenizer:
    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"<%d>" % tok


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


@pytest.fixture()
def make_engine(params):
    def factory(chaos=None, **overrides):
        kw = dict(slots=4, temperature=0.0, topp=0.9, seed=7,
                  metrics=Registry(), prefill_chunk=4, page_size=4,
                  kv_pages=20)
        kw.update(overrides)
        return ContinuousEngine(SPEC, params, chaos=chaos, **kw)

    return factory


# -------------------------------------------------------------- audit


def test_audit_clean_and_each_violation_kind():
    alloc = PagedAllocator(n_pages=6, page_size=4)
    assert alloc.audit([]) == []
    a, b = alloc.alloc_page(), alloc.alloc_page()
    assert alloc.audit([[a], [b]]) == []
    # leak: allocated page that no slot or tree node maps
    leak = alloc.audit([[a]])
    assert any("leaked" in p and str(b) in p for p in leak)
    # use-after-free in waiting: slot maps a page the pool freed
    alloc.release_pages([b])
    uaf = alloc.audit([[a], [b]])
    assert any(f"page {b}" in p and "refcount" in p for p in uaf)
    # refcount mismatch: double-mapped page with a single ref
    bad = alloc.audit([[a], [a]])
    assert any("refcount 1 != 2" in p for p in bad)
    # scrap page must never be mapped
    scrap = alloc.audit([[0]])
    assert any("scrap" in p for p in scrap)


def test_audit_accounts_tree_references():
    alloc = PagedAllocator(n_pages=6, page_size=2)
    pages = [alloc.alloc_page(), alloc.alloc_page()]
    tokens = [9, 8, 7, 6]  # two full pages
    alloc.insert_prefix(tokens, pages)
    # slot + tree each hold a ref
    assert alloc.audit([pages]) == []
    alloc.release_pages(pages)  # tree keeps them alive
    assert alloc.audit([]) == []
    assert alloc.pool.refcount(pages[0]) == 1


def test_scrape_problems_flags_broken_exposition():
    class _Bad:
        def expose(self):
            raise RuntimeError("boom")

    assert scrape_problems(None) == []
    assert scrape_problems(Registry()) == []
    assert any("boom" in p for p in scrape_problems(_Bad()))


# -------------------------------------------------------- ChaosMonkey


def test_chaos_monkey_parse_and_determinism():
    m = ChaosMonkey.parse(
        "step_delay_every=3,step_delay_ms=1,deny_pages=2,leak_on_cancel=1")
    assert m.step_delay_every == 3
    assert m.step_delay_s == pytest.approx(0.001)
    assert m.deny_pages == 2 and m.leak_on_cancel
    assert ChaosMonkey.parse("leak_on_cancel=0").leak_on_cancel is False
    with pytest.raises(ValueError):
        ChaosMonkey.parse("nope=1")
    with pytest.raises(ValueError):
        ChaosMonkey.parse("step_delay_every")
    # denial is a counter, not a coin: exactly N denials then clean
    m = ChaosMonkey(deny_pages=2)
    assert [m.deny_page() for _ in range(4)] == [True, True, False, False]
    # delay fires on every Nth dispatch exactly
    m = ChaosMonkey(step_delay_every=2, step_delay_s=0.0001)
    for _ in range(5):
        m.on_dispatch()
    assert m.injected_delays == 2


# ------------------------------------------------------------- cancel


def test_cancel_queued_request_completes_immediately(params):
    eng = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                           topp=0.9, seed=5, metrics=Registry())
    first = Request(tokens=[1, 5, 9], steps=SPEC.seq_len)
    queued = Request(tokens=[1, 7], steps=SPEC.seq_len)
    eng.submit(first)
    eng.step_once()  # first occupies the only slot
    eng.submit(queued)
    eng.cancel(queued)  # still queued: completes NOW, no scheduler needed
    assert queued.done.is_set() and queued.cancelled
    reg = eng._obs.registry
    assert reg.get("dllama_requests_cancelled_total").value == 1
    assert reg.get("dllama_queue_depth").value == 0
    first.cancelled = True  # drain the slot for a clean engine
    while eng.step_once():
        pass


def test_cancel_in_flight_frees_pages_at_next_sweep(params):
    """The satellite-1 engine half: cancel() on a decoding request frees
    its slot AND pages at the next scheduler touch (the pre-dispatch
    sweep), not after another full chain of decoding."""
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=Registry(),
                           page_size=4, block_steps=8)
    free0 = eng.allocator.n_free
    req = Request(tokens=[1, 5, 9, 11, 13], steps=SPEC.seq_len)
    eng.submit(req)
    eng.step_many(2)  # two steps: still mid-prompt-echo, pages held
    held = next(len(s.pages) for s in eng._pool if not s.free)
    assert held > 0
    tokens_at_cancel = len(req.out)
    eng.cancel(req)
    eng.step_many(eng.block_steps)  # sweep runs before the next dispatch
    assert req.done.is_set()
    # the sweep retired it BEFORE dispatching another chain: no further
    # tokens were decoded for the vanished consumer
    assert len(req.out) == tokens_at_cancel
    assert eng.allocator.n_free == free0  # cancelled publishes nothing
    assert eng.audit_pages() == []
    reg = eng._obs.registry
    assert reg.get("dllama_kv_pages_free").value == free0


# -------------------------------------------------------------- drills


def test_all_drills_pass_on_healthy_engine(make_engine):
    from distributed_llama_tpu.runtime.chaos import DRILLS

    assert [name for name, _ in DRILLS] == [
        "pool_exhaustion", "transient_starvation", "oversized_prompt",
        "disconnect", "latency_spike", "profiler_under_load",
        "tier_spill_storm", "journal_wal", "kill_mid_handoff",
        "kill_mid_decode", "hung_dispatch", "weight_stream_disconnect"]
    # kill_mid_decode spawns a jax subprocess and kill_mid_handoff
    # drives full two-pool engines — each has its own slow-marked test
    # (here + tests/test_disagg.py); everything else runs here
    which = {name for name, _ in DRILLS} - {"kill_mid_decode",
                                            "kill_mid_handoff"}
    results = run_drills(make_engine, which=which)
    assert len(results) == len(which)
    assert all(r.passed for r in results), [
        (r.name, r.violations) for r in results if not r.passed]
    # the drills actually exercised their faults
    by_name = {r.name: r for r in results}
    assert by_name["pool_exhaustion"].details["pauses"] > 0
    assert by_name["transient_starvation"].details["denied_allocs"] == 6
    assert by_name["latency_spike"].details["injected_delays"] > 0
    assert by_name["disconnect"].details["pages_at_risk"] > 0
    assert by_name["hung_dispatch"].details["trips"] > 0
    assert by_name["weight_stream_disconnect"].details["drops"] > 0
    storm = by_name["tier_spill_storm"].details
    assert storm["prefill_saved_spilled"] > 0
    assert sum(storm["demotions"].values()) > 0
    assert sum(storm["promotions"].values()) > 0


def test_kill_mid_decode_drill_recovers_bitwise(make_engine):
    """The crash-safety acceptance drill (ISSUE 9): SIGKILL a journaling
    subprocess mid-decode; the recovered continuation must be bitwise the
    uninterrupted reference for greedy AND seeded-sampled requests, with
    a clean page audit."""
    results = run_drills(make_engine, which={"kill_mid_decode"})
    assert len(results) == 1
    r = results[0]
    assert r.passed, r.violations
    assert r.details["recovered"] == 2
    assert r.details["replayed_tokens"] >= 4


def test_corrupt_journal_turns_kill_drill_red(make_engine):
    """The recovery gate's mutation arm: a byte smashed MID-journal before
    recovery must raise JournalCorruption and fail the drill — proving
    tools/ci.sh's exit-1 assertion can actually fire."""
    results = run_drills(make_engine, which={"kill_mid_decode"},
                         inject={"corrupt-journal"})
    assert len(results) == 1 and not results[0].passed
    assert any("JournalCorruption" in v for v in results[0].violations)


def test_seeded_leak_turns_disconnect_drill_red(make_engine):
    """The gate's mutation arm: leak_on_cancel must be CAUGHT by the
    disconnect drill's audit (kv_pages_free round-trip + page audit)."""

    def leaky(chaos=None, **overrides):
        if chaos is None:
            chaos = ChaosMonkey(leak_on_cancel=True)
        else:
            chaos.leak_on_cancel = True
        return make_engine(chaos=chaos, **overrides)

    results = run_drills(leaky, which={"disconnect"})
    assert len(results) == 1 and not results[0].passed
    text = " ".join(results[0].violations)
    assert "leaked" in text and "round-trip" in text


def test_check_invariants_passes_fresh_and_flags_leak(make_engine):
    eng = make_engine()
    assert check_invariants(eng) == []
    # hand-build a leak: allocate a page no slot list will ever explain
    eng.allocator.alloc_page()
    assert any("leaked" in p for p in check_invariants(eng))


# ------------------------------------------- server-level regression


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.read()


def _metric_value(port, name):
    for line in _get(port, "/metrics").decode().splitlines():
        if line.startswith(name + " "):
            return float(line.rsplit(" ", 1)[1])
    raise AssertionError(f"{name} not in /metrics")


def test_server_stream_disconnect_frees_kv_pages(params):
    """Satellite 1, drill-backed: a client vanishing mid-stream must free
    the slot AND its KV pages immediately (engine.cancel + pre-dispatch
    sweep), counted via dllama_kv_pages_free before/after."""
    import http.client
    import time

    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=SPEC.seq_len, temperature=0.0,
                          topp=0.9, seed=5, quiet=True, page_size=4,
                          block_steps=4)
    srv.start()
    try:
        free_before = _metric_value(srv.port, "dllama_kv_pages_free")
        assert free_before == srv.engine.allocator.n_pages
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=30)
        conn.request("POST", "/generate",
                     body=json.dumps({"prompt": "hello there",
                                      "steps": SPEC.seq_len,
                                      "stream": True}))
        resp = conn.getresponse()
        resp.read(1)  # the request is decoding in a slot, pages held
        conn.close()  # vanish mid-stream

        deadline = time.time() + 30
        while time.time() < deadline:
            h = json.loads(_get(srv.port, "/health"))
            if h["active"] == 0 and h["queued"] == 0:
                break
            time.sleep(0.05)
        assert h["active"] == 0 and h["queued"] == 0, h
        # every page came back: a cancelled request publishes nothing to
        # the radix tree, so free must round-trip exactly
        deadline = time.time() + 10
        while time.time() < deadline:
            if _metric_value(srv.port, "dllama_kv_pages_free") \
                    == free_before:
                break
            time.sleep(0.05)
        assert _metric_value(srv.port, "dllama_kv_pages_free") \
            == free_before
        assert srv.engine.audit_pages() == []
    finally:
        srv.stop()


def test_server_oversized_prompt_rejected_and_counted(params):
    from distributed_llama_tpu.runtime.server import InferenceServer

    srv = InferenceServer(SPEC, params, _IdTokenizer(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True)
    srv.start()
    try:
        body = json.dumps({"prompt": "x" * (2 * SPEC.seq_len),
                           "steps": 8}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400
        assert "seq_len" in json.loads(ei.value.read())["error"]
        text = _get(srv.port, "/metrics").decode()
        assert ('dllama_admission_rejected_total{reason="oversized"} 1'
                in text)
        h = json.loads(_get(srv.port, "/health"))
        assert h["admission_rejected"]["oversized"] == 1
    finally:
        srv.stop()
