"""End-to-end generation loop + CLI tests on a tiny synthetic model."""

import contextlib
import io
import os

import numpy as np
import pytest

from distributed_llama_tpu.io.loader import write_model
from distributed_llama_tpu.io.tokenizer import Tokenizer, write_tokenizer
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=300, seq_len=32,
                       weights_float_type=FloatType.Q40)


@pytest.fixture(scope="module")
def model_files(tmp_path_factory):
    d = tmp_path_factory.mktemp("m")
    rng = np.random.default_rng(5)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.1).astype(np.float32)

    tensors = {"tok_embedding": t(SPEC.vocab_size, SPEC.dim),
               "rms_att": 1 + t(SPEC.n_layers, SPEC.dim),
               "rms_ffn": 1 + t(SPEC.n_layers, SPEC.dim),
               "rms_final": 1 + t(SPEC.dim),
               "wcls": t(SPEC.vocab_size, SPEC.dim)}
    for name, shape in SPEC.layer_matmul_shapes():
        tensors[name] = t(SPEC.n_layers, *shape)
    model = str(d / "model.bin")
    write_model(model, SPEC, tensors)

    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    pieces += [b" ", b"h", b"i", b"hi", b" hi"]  # up to vocab 300: pad
    while len(pieces) < SPEC.vocab_size:
        pieces.append(f"tok{len(pieces)}".encode())
    scores = [0.0] * len(pieces)
    scores[pieces.index(b"hi")] = -0.5
    scores[pieces.index(b" hi")] = -0.4
    tok = str(d / "tok.bin")
    write_tokenizer(tok, pieces, scores)
    return model, tok


def test_generate_greedy(model_files):
    from distributed_llama_tpu.io.loader import load_model
    from distributed_llama_tpu.runtime.generate import Engine, generate
    from distributed_llama_tpu.runtime.sampling import Sampler

    model, tokp = model_files
    spec, params = load_model(model, weights_float_type=FloatType.Q40)
    engine = Engine(spec, params)
    tok = Tokenizer(tokp, spec.vocab_size)
    sampler = Sampler(spec.vocab_size, temperature=0.0, topp=0.9, seed=1)
    out1, stats = generate(engine, tok, sampler, "hi", steps=8, quiet=True)
    assert stats.tokens == 8
    assert stats.total_ms > 0 and stats.infer_ms > 0

    # deterministic: same prompt, fresh engine -> same tokens
    engine.reset()
    out2, _ = generate(engine, tok, sampler, "hi", steps=8, quiet=True)
    assert out1 == out2


def test_generate_respects_seq_len(model_files):
    from distributed_llama_tpu.io.loader import load_model
    from distributed_llama_tpu.runtime.generate import Engine, generate
    from distributed_llama_tpu.runtime.sampling import Sampler

    model, tokp = model_files
    spec, params = load_model(model, weights_float_type=FloatType.Q40)
    engine = Engine(spec, params)
    tok = Tokenizer(tokp, spec.vocab_size)
    sampler = Sampler(spec.vocab_size, 0.0, 0.9, seed=1)
    out, stats = generate(engine, tok, sampler, "hi", steps=10_000, quiet=True)
    assert stats.tokens <= spec.seq_len


def test_cli_inference_smoke(model_files, capsys):
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    rc = main(["inference", "--model", model, "--tokenizer", tokp,
               "--prompt", "hi", "--steps", "4", "--temperature", "0",
               "--weights-float-type", "q40", "--tp", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "💡 dim: 64" in out
    assert "🔶" in out  # per-token stats lines
    assert "Avg generation time" in out


def test_cli_save_resume_roundtrip(model_files, tmp_path, capsys):
    """CLI --save-state / --resume-state: split run == unsplit run."""
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    base = ["--model", model, "--tokenizer", tokp, "--temperature", "0.9",
            "--topp", "0.9", "--seed", "42", "--tp", "1"]
    assert main(["inference", *base, "--prompt", "hi", "--steps", "10"]) == 0
    full = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("🔶")]

    ckpt = str(tmp_path / "gen.ckpt")  # no .npz suffix on purpose
    assert main(["inference", *base, "--prompt", "hi", "--steps", "4",
                 "--save-state", ckpt]) == 0
    part1 = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("🔶")]
    assert main(["inference", *base, "--steps", "6",
                 "--resume-state", ckpt]) == 0
    out2 = capsys.readouterr().out
    assert f"({len(part1)} tokens so far)" in out2
    part2 = [ln for ln in out2.splitlines() if ln.startswith("🔶")]

    def pieces(lines):
        return [ln.rsplit("'", 2)[-2] for ln in lines]

    assert pieces(part1) + pieces(part2) == pieces(full)


def test_cli_worker_requires_coordinator(capsys):
    from distributed_llama_tpu.frontend.cli import main

    assert main(["worker", "--port", "9998"]) == 2


def test_cli_unknown_mode():
    from distributed_llama_tpu.frontend.cli import main

    assert main(["frobnicate"]) == 1


def test_cli_serve_rejects_bad_slots(model_files):
    """serve validates --slots before loading anything or binding a port."""
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--slots", "0"]) == 2
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--slots", "-2"]) == 2


def test_cli_spec_k_requires_page_size_at_argparse_time(model_files,
                                                        tmp_path, capsys):
    """--spec-k without --kv-page-size fails BEFORE the model load with
    the one-line actionable error, on BOTH inference and serve (ISSUE 10
    small fix: this used to surface deep in engine construction)."""
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    empty = tmp_path / "prompts.txt"
    empty.write_text("")
    assert main(["inference", "--model", model, "--tokenizer", tokp,
                 "--prompts-file", str(empty), "--continuous",
                 "--spec-k", "4"]) == 2
    assert "--kv-page-size" in capsys.readouterr().err
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--spec-k", "4"]) == 2
    assert "--kv-page-size" in capsys.readouterr().err
    # the valid pairing proceeds past the gate and fails later, on the
    # empty prompts file — proving the gate ran (and passed) first
    rc = main(["inference", "--model", model, "--tokenizer", tokp,
               "--prompts-file", str(empty), "--continuous",
               "--spec-k", "4", "--kv-page-size", "4"])
    err = capsys.readouterr().err
    assert rc == 2 and "empty" in err and "--kv-page-size" not in err


def test_cli_kv_quant_requires_page_size_at_argparse_time(model_files,
                                                          tmp_path,
                                                          capsys):
    """--kv-quant q8 without --kv-page-size fails BEFORE the model load
    with the one-line actionable error, on BOTH inference and serve
    (ISSUE 11: q8 quantizes PAGE planes — meaningless without the paged
    pool), exactly like the --spec-k gate above."""
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    empty = tmp_path / "prompts.txt"
    empty.write_text("")
    # the CLI flag sets DLLAMA_KV_QUANT (the DLLAMA_TP_SCHEME pattern) —
    # scrub it on exit so later CLI tests see the default
    try:
        assert main(["inference", "--model", model, "--tokenizer", tokp,
                     "--prompts-file", str(empty), "--continuous",
                     "--kv-quant", "q8"]) == 2
        assert "--kv-page-size" in capsys.readouterr().err
        assert main(["serve", "--model", model, "--tokenizer", tokp,
                     "--kv-quant", "q8"]) == 2
        assert "--kv-page-size" in capsys.readouterr().err
        # an unknown quant mode is an argparse error (SystemExit 2)
        with pytest.raises(SystemExit):
            main(["serve", "--model", model, "--tokenizer", tokp,
                  "--kv-quant", "int4"])
        capsys.readouterr()
        # the DLLAMA_KV_QUANT env knob alone resolves through the same
        # gate (flag sets env; env without the flag also works)
        os.environ["DLLAMA_KV_QUANT"] = "q8"
        assert main(["serve", "--model", model, "--tokenizer",
                     tokp]) == 2
        assert "--kv-page-size" in capsys.readouterr().err
        # the valid pairing proceeds past the gate and fails later, on
        # the empty prompts file — proving the gate ran (and passed)
        # first (env still q8 from above)
        rc = main(["inference", "--model", model, "--tokenizer", tokp,
                   "--prompts-file", str(empty), "--continuous",
                   "--kv-quant", "q8", "--kv-page-size", "4"])
        err = capsys.readouterr().err
        assert rc == 2 and "empty" in err and "--kv-page-size" not in err
    finally:
        os.environ.pop("DLLAMA_KV_QUANT", None)


def test_cli_overlap_scheme_rejects_sp_at_argparse_time(model_files,
                                                        capsys):
    """--tp-scheme overlap with --sp > 1 fails at argparse time: the
    ring-decomposed combines assume un-chunked sequences."""
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    assert main(["inference", "--model", model, "--tokenizer", tokp,
                 "--tp-scheme", "overlap", "--sp", "2"]) == 2
    err = capsys.readouterr().err
    assert "overlap" in err and "--sp 1" in err


def test_cli_batch_prompts_file(model_files, tmp_path, capsys):
    """--prompts-file decodes B prompts in one lockstep batch; greedy rows
    must equal the corresponding single-prompt runs."""
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    base = ["--model", model, "--tokenizer", tokp, "--temperature", "0",
            "--steps", "6", "--tp", "1"]

    singles = []
    for p in ("hi", "hi hi"):
        assert main(["inference", *base, "--prompt", p]) == 0
        out = capsys.readouterr().out
        singles.append([ln.rsplit("'", 2)[-2]
                        for ln in out.splitlines() if ln.startswith("🔶")])

    pf = tmp_path / "prompts.txt"
    pf.write_text("hi\nhi hi\n")
    assert main(["inference", *base, "--prompts-file", str(pf)]) == 0
    out = capsys.readouterr().out
    rows = [ln for ln in out.splitlines() if ln.startswith("[")]
    assert len(rows) == 2
    for b, single in enumerate(singles):
        assert rows[b].startswith(f"[{b}] ")
        # the batch row's decoded text == concatenation of the single run's
        # per-token pieces
        assert rows[b].split(" ", 1)[1] == repr("".join(single))

    # same batch over a tp=2 mesh (sharded lockstep step): identical rows
    assert main(["inference", *base[:-2], "--tp", "2",
                 "--prompts-file", str(pf)]) == 0
    out = capsys.readouterr().out
    rows_tp = [ln for ln in out.splitlines() if ln.startswith("[")]
    assert rows_tp == rows

    # batch over an sp=2 mesh (sequence-chunked cache + per-row LSE
    # combine): identical rows again
    assert main(["inference", *base[:-2], "--tp", "1", "--sp", "2",
                 "--prompts-file", str(pf)]) == 0
    out = capsys.readouterr().out
    rows_sp = [ln for ln in out.splitlines() if ln.startswith("[")]
    assert rows_sp == rows

    # continuous batching through a 1-slot pool: the two prompts stream
    # through sequentially; greedy rows must still match
    assert main(["inference", *base[:-2], "--tp", "1", "--continuous",
                 "--slots", "1", "--prompts-file", str(pf)]) == 0
    out = capsys.readouterr().out
    rows_cont = [ln for ln in out.splitlines() if ln.startswith("[")
                 and "] done:" not in ln]
    assert rows_cont == rows

    # continuous batching over a tp=2 mesh: identical rows again
    assert main(["inference", *base[:-2], "--tp", "2", "--continuous",
                 "--slots", "2", "--prompts-file", str(pf)]) == 0
    out = capsys.readouterr().out
    rows_ctp = [ln for ln in out.splitlines() if ln.startswith("[")
                and "] done:" not in ln]
    assert rows_ctp == rows

    # flag misuse is rejected up front, not silently ignored
    assert main(["inference", *base, "--continuous",
                 "--prompt", "hi"]) == 2                   # no prompts-file
    assert main(["inference", *base[:-2], "--tp", "1", "--continuous",
                 "--slots", "-3", "--prompts-file", str(pf)]) == 2
    # lockstep batch can't prefill (shared position clock)
    assert main(["inference", *base[:-2], "--tp", "1", "--prefill-chunk",
                 "4", "--prompts-file", str(pf)]) == 2


def test_cli_dispatch_tokens_validates_at_argparse_time(model_files,
                                                        tmp_path, capsys):
    """ISSUE 18: --dispatch-tokens fails BEFORE the model load when
    paired with --spec-k (both widen the per-row span; the engine prices
    ONE dispatch shape) or used without --kv-page-size (mixed spans need
    the paged pool), on BOTH inference and serve."""
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    empty = tmp_path / "prompts.txt"
    empty.write_text("")
    assert main(["inference", "--model", model, "--tokenizer", tokp,
                 "--prompts-file", str(empty), "--continuous",
                 "--kv-page-size", "4", "--spec-k", "4",
                 "--dispatch-tokens", "16"]) == 2
    assert "--spec-k" in capsys.readouterr().err
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--kv-page-size", "4", "--spec-k", "4",
                 "--dispatch-tokens", "16"]) == 2
    assert "--spec-k" in capsys.readouterr().err
    assert main(["inference", "--model", model, "--tokenizer", tokp,
                 "--prompts-file", str(empty), "--continuous",
                 "--dispatch-tokens", "16"]) == 2
    assert "--kv-page-size" in capsys.readouterr().err
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--dispatch-tokens", "16"]) == 2
    assert "--kv-page-size" in capsys.readouterr().err
    # the valid pairing proceeds past the gate and fails later, on the
    # empty prompts file — proving the gate ran (and passed) first
    rc = main(["inference", "--model", model, "--tokenizer", tokp,
               "--prompts-file", str(empty), "--continuous",
               "--dispatch-tokens", "16", "--kv-page-size", "4"])
    err = capsys.readouterr().err
    assert rc == 2 and "empty" in err and "--kv-page-size" not in err


def test_cli_disagg_flags_validate_at_argparse_time(model_files, capsys):
    """ISSUE 14: the disaggregation knobs fail BEFORE the model load —
    role without --kv-page-size, decode without a peer, a peer without
    the decode role, and a nonsense handoff threshold."""
    from distributed_llama_tpu.frontend.cli import main

    model, tokp = model_files
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--disagg-role", "prefill"]) == 2
    assert "--kv-page-size" in capsys.readouterr().err
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--disagg-role", "decode", "--kv-page-size", "4"]) == 2
    assert "--disagg-peer" in capsys.readouterr().err
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--kv-page-size", "4",
                 "--disagg-peer", "127.0.0.1:1"]) == 2
    assert "--disagg-role decode" in capsys.readouterr().err
    assert main(["serve", "--model", model, "--tokenizer", tokp,
                 "--disagg-role", "decode", "--kv-page-size", "4",
                 "--disagg-peer", "127.0.0.1:1",
                 "--handoff-min-pages", "0"]) == 2
    assert "--handoff-min-pages" in capsys.readouterr().err
