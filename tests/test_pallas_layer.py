"""Fused per-layer kernel parity (ops/pallas_layer, VERDICT r2 #2).

The fused head/tail kernels run in interpret mode here; the value map must
match the unfused forward (same Q40 dequant math, same rmsnorm/silu/RoPE
formulas) to float-associativity noise.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

SPEC = TransformerSpec(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=32,
                       weights_float_type=FloatType.Q40)


def _packed(spec, seed=11, with_spec=False):
    from distributed_llama_tpu.models.llama import params_to_device
    from distributed_llama_tpu.models.synth import synth_params

    return params_to_device(synth_params(spec, q40=True, seed=seed,
                                         scale=0.2),
                            spec=spec if with_spec else None)


# head_size=128 shapes (the megakernel's attention layout): MHA and GQA
MEGA_MHA = TransformerSpec(dim=256, hidden_dim=96, n_layers=2, n_heads=2,
                           n_kv_heads=2, vocab_size=64, seq_len=16,
                           weights_float_type=FloatType.Q40)
MEGA_GQA = TransformerSpec(dim=512, hidden_dim=160, n_layers=2, n_heads=4,
                           n_kv_heads=2, vocab_size=64, seq_len=16,
                           weights_float_type=FloatType.Q40)


def _packed_d_major(spec, seed=11):
    """Mega-kernel packing at TEST dims: tiny nb values make the auto
    packer pick nb-major (its lane-padding heuristic), which excludes the
    d-major-only mega path — 7B's nb=128 picks d-major naturally. Force
    d-major + mega prep here."""
    import jax

    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.ops.linear import (fuse_q40_layer_matmuls,
                                                  pack_q40_params)
    from distributed_llama_tpu.ops.pallas_layer import prepare_mega_params

    params = synth_params(spec, q40=True, seed=seed, scale=0.2)
    params = fuse_q40_layer_matmuls(
        pack_q40_params(params, enable=True, allow_nb_major=False))
    params = prepare_mega_params(spec, params)
    return jax.tree_util.tree_map(jnp.asarray, params)


def test_supports_gating(monkeypatch):
    from distributed_llama_tpu.ops import pallas_layer

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    params = _packed(SPEC)
    assert pallas_layer.supports(SPEC, params)
    # Q80 buffer mode is out of scope for the fused path
    spec80 = TransformerSpec(**{**SPEC.__dict__,
                                "buffer_float_type": FloatType.Q80})
    assert not pallas_layer.supports(spec80, params)
    # codec-layout (unpacked) weights: no fused path
    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "xla")
    assert not pallas_layer.supports(SPEC, _packed(SPEC))


@pytest.mark.parametrize("spec", [
    SPEC,
    # GQA shape (kv_mul=2) at a different head size
    TransformerSpec(dim=128, hidden_dim=160, n_layers=2, n_heads=4,
                    n_kv_heads=2, vocab_size=96, seq_len=16,
                    weights_float_type=FloatType.Q40),
])
def test_fused_decode_matches_unfused(monkeypatch, spec):
    """A multi-step greedy decode chain through the fused path must match
    the unfused kernel path step for step (logits to float-assoc noise,
    tokens exactly)."""
    from distributed_llama_tpu.models.llama import forward, init_cache

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    params = _packed(spec)

    def run(steps=5):
        cache = init_cache(spec)
        tok = jnp.asarray([3], jnp.int32)
        logits_all, toks = [], []
        for pos in range(steps):
            logits, cache = forward(spec, params, cache, tok,
                                    jnp.int32(pos))
            logits_all.append(np.asarray(logits[0]))
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            toks.append(int(tok[0]))
        return np.stack(logits_all), toks

    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "off")
    want, want_toks = run()
    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "on")
    got, got_toks = run()
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=1e-4)
    assert got_toks == want_toks


def test_headtail_mode_skips_mega_and_matches(monkeypatch):
    """DLLAMA_LAYER_FUSION=headtail (r4 launch-tax attempt #2) builds the
    head/tail pair WITHOUT the megakernel: prepare_mega_params must not
    add wo_mega, fusion_cache_key must distinguish the tree, and the
    decode must match the unfused path."""
    from distributed_llama_tpu.models.llama import forward, init_cache
    from distributed_llama_tpu.ops.pallas_layer import (fusion_cache_key,
                                                        prepare_mega_params)

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    spec = SPEC
    params = _packed(spec)

    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "headtail")
    assert fusion_cache_key() == "headtail"
    assert "wo_mega" not in prepare_mega_params(spec, params)
    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "on")
    assert fusion_cache_key() == "mega"
    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "off")
    assert fusion_cache_key() == "off"

    def run():
        cache = init_cache(spec)
        logits, cache = forward(spec, params, cache,
                                jnp.asarray([3], jnp.int32), jnp.int32(0))
        logits2, _ = forward(spec, params, cache,
                             jnp.asarray([7], jnp.int32), jnp.int32(1))
        return np.asarray(logits[0]), np.asarray(logits2[0])

    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "off")
    want = run()
    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "headtail")
    got = run()
    np.testing.assert_allclose(got[0], want[0], atol=5e-4, rtol=1e-4)
    np.testing.assert_allclose(got[1], want[1], atol=5e-4, rtol=1e-4)

    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "dequnat")
    with pytest.raises(ValueError):
        fusion_cache_key()


def test_fused_after_prefill(monkeypatch):
    """Prefill (T>1, unfused — fusion is T=1-only) then fused decode must
    equal the fully unfused run: the two paths share one cache layout."""
    from distributed_llama_tpu.models.llama import forward, init_cache

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    spec = SPEC
    params = _packed(spec)
    prompt = jnp.asarray([3, 7, 11], jnp.int32)

    def run():
        cache = init_cache(spec)
        _, cache = forward(spec, params, cache, prompt, jnp.int32(0))
        logits, cache = forward(spec, params, cache,
                                jnp.asarray([5], jnp.int32), jnp.int32(3))
        return np.asarray(logits[0])

    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "off")
    want = run()
    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "on")
    got = run()
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=1e-4)


def test_fused_kernels_cut_op_count(monkeypatch):
    """The point of the fusion: the per-layer program collapses to the two
    fused pallas_calls (+ attention). Count custom_call/pallas eqns in the
    jaxpr's scan body."""
    from distributed_llama_tpu.analysis.jaxpr_contracts import walk_fn_eqns

    from distributed_llama_tpu.models.llama import forward, init_cache

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "on")
    params = _packed(SPEC)
    tok = jnp.asarray([3], jnp.int32)

    import functools

    fn = functools.partial(forward, SPEC)
    names = [e.primitive.name
             for e in walk_fn_eqns(fn, params, init_cache(SPEC), tok,
                                   jnp.int32(0))]
    # exactly two pallas_calls inside the scan body (head + tail; the
    # interpret-mode attention fallback is XLA einsum here) plus the wcls
    # matvec after the scan — an exact count, so a regression back to ~10
    # per-layer calls fails loudly
    assert names.count("pallas_call") == 3


@pytest.mark.parametrize("spec", [MEGA_MHA, MEGA_GQA])
def test_mega_decode_matches_unfused(monkeypatch, spec):
    """The whole-layer megakernel (1 pallas_call per layer, in-kernel
    attention + cache write via aliased outputs) must match the unfused
    path: logits per step AND the final cache content (which pins the
    input_output_aliases indices and the (layer, pos) write placement)."""
    from distributed_llama_tpu.models.llama import forward, init_cache
    from distributed_llama_tpu.ops import pallas_layer

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "on")
    params = _packed_d_major(spec)
    assert pallas_layer.mega_supported(spec, params), "mega prep missing"

    def run(use):
        monkeypatch.setenv("DLLAMA_LAYER_FUSION", use)
        p = dict(params)
        if use == "off":
            p.pop("wo_mega", None)
        cache = init_cache(spec)
        tok = jnp.asarray([3], jnp.int32)
        logits_all, toks = [], []
        for pos in range(5):
            logits, cache = forward(spec, p, cache, tok, jnp.int32(pos))
            logits_all.append(np.asarray(logits[0]))
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            toks.append(int(tok[0]))
        return np.stack(logits_all), toks, cache

    want, want_toks, want_cache = run("off")
    got, got_toks, got_cache = run("on")
    np.testing.assert_allclose(got, want, atol=5e-4, rtol=1e-4)
    assert got_toks == want_toks
    # K carries RoPE: the in-kernel cos/sin differ from XLA's by a few
    # 1e-5 ABSOLUTE (different transcendental lowerings; relative error is
    # unbounded near zero crossings); V is rotation-free
    np.testing.assert_allclose(np.asarray(got_cache.k),
                               np.asarray(want_cache.k), atol=1e-4)
    # V inherits the in-kernel rmsnorm's reduction-order noise through the
    # wqkv dot (~1e-7 relative on xb, amplified by the contraction)
    np.testing.assert_allclose(np.asarray(got_cache.v),
                               np.asarray(want_cache.v), atol=1e-4)


def test_mega_one_op_per_layer(monkeypatch):
    """The fused T=1 program must contain exactly ONE pallas_call (the
    megakernel) in its layer scan body."""
    import functools

    from distributed_llama_tpu.analysis.jaxpr_contracts import walk_fn_eqns

    from distributed_llama_tpu.models.llama import forward, init_cache

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    monkeypatch.setenv("DLLAMA_LAYER_FUSION", "on")
    params = _packed_d_major(MEGA_MHA)
    fn = functools.partial(forward, MEGA_MHA)
    eqns = list(walk_fn_eqns(fn, params, init_cache(MEGA_MHA),
                             jnp.asarray([3], jnp.int32), jnp.int32(0)))
    # one megakernel inside the scan + the wcls matmul outside of it
    assert [e.primitive.name for e in eqns].count("pallas_call") <= 2
