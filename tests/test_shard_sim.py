"""parallel/shard_sim.py: one-rank-on-one-chip execution (the 70B bench path).

Honesty gates: the sim must run the SAME local program as the real tp mesh
(tp.make_local_step is shared code, only gather_fn differs), so
(a) with n_slices=1 the sim IS the full model — logits must match the
    single-chip forward;
(b) with n_slices>1 the op inventory must match the real shard_map program's
    per-rank body (same matmul count/shapes — the tile only replaces the
    collective);
(c) the analytic projection must be internally consistent with comm_stats.
"""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.ops.quants import FloatType
from distributed_llama_tpu.parallel import shard_sim

# hidden_dim 256: the fused scheme slices w2's Q40 input dim (hidden/S must
# be a 32-multiple), like every real model shape (7B 11008/8=1376, ...)
SPEC = TransformerSpec(dim=64, hidden_dim=256, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


def test_sim_tp1_equals_single_chip_forward():
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)

    params = synth_params(SPEC, q40=False, seed=9, scale=0.2)
    tokens = jnp.asarray([3, 11], jnp.int32)

    dev = params_to_device(params)
    want, _ = forward(SPEC, dev, init_cache(SPEC), tokens, jnp.int32(0))

    fwd = shard_sim.make_rank_forward(SPEC, 1)
    got, _ = fwd(dev, shard_sim.init_rank_cache(SPEC, 1), tokens,
                 jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def _dot_shapes(fn, *args):
    from distributed_llama_tpu.analysis.jaxpr_contracts import walk_fn_eqns

    return sorted(tuple(tuple(v.aval.shape) for v in e.invars)
                  for e in walk_fn_eqns(fn, *args)
                  if e.primitive.name in ("dot_general", "einsum"))


def test_sim_matches_real_rank_program_structure():
    """The sim's matmul inventory == the real tp=2 shard_map rank body's
    (same shapes op for op; only the gather differs)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import init_cache
    from distributed_llama_tpu.parallel import (make_mesh,
                                                make_sharded_forward,
                                                shard_cache, shard_params)

    params = synth_params(SPEC, q40=False, seed=9, scale=0.2)
    tokens = jnp.asarray([3], jnp.int32)
    mesh = make_mesh(tp=2)
    sp = shard_params(params, mesh)
    real = make_sharded_forward(SPEC, mesh)
    real_shapes = _dot_shapes(real, sp, shard_cache(init_cache(SPEC), mesh),
                              tokens, jnp.int32(0))

    bands = shard_sim.synth_rank_q40(SPEC, 2)
    # densify: the structure comparison needs the same dense-matmul lowering
    # as the CPU-mesh real program (Q40 takes the kernel path on TPU only)
    from distributed_llama_tpu.ops.linear import dequantize_weight

    dense = {k: (np.asarray(dequantize_weight(v))
                 if hasattr(v, "qs") else v) for k, v in bands.items()}
    dense = shard_sim.rank_params_to_device(dense)
    sim = shard_sim.make_rank_forward(SPEC, 2)
    sim_shapes = _dot_shapes(sim, dense, shard_sim.init_rank_cache(SPEC, 2),
                             tokens, jnp.int32(0))
    assert sim_shapes == real_shapes


def test_sim_band_shapes_and_cache():
    bands = shard_sim.synth_rank_q40(SPEC, 2)  # default scheme: fused
    assert bands["wq"].logical_shape == (2, 32, 64)       # (L, dim/2, dim)
    assert bands["wk"].logical_shape == (2, 16, 64)       # (L, kv_dim/2, dim)
    assert bands["w1"].logical_shape == (2, 128, 64)      # (L, hidden/2, dim)
    assert bands["wo"].logical_shape == (2, 64, 32)       # (L, dim, dim/2)
    assert bands["w2"].logical_shape == (2, 64, 128)      # (L, dim, hidden/2)
    assert bands["wcls"].logical_shape == (64, 64)        # (vocab/2, dim)
    assert bands["tok_embedding"].shape == (128, 64)      # replicated, full
    ref = shard_sim.synth_rank_q40(SPEC, 2, scheme="ref")
    assert ref["wo"].logical_shape == (2, 32, 64)         # (L, dim/2, dim)
    assert ref["w2"].logical_shape == (2, 32, 256)        # (L, dim/2, hidden)
    cache = shard_sim.init_rank_cache(SPEC, 2)
    assert cache.k.shape == (2, 16, 1, 16)                # 1 kv head local
    with pytest.raises(ValueError, match="divide"):
        shard_sim.synth_rank_q40(SPEC, 3)
    # fused input-dim bands must stay whole Q40 blocks
    narrow = TransformerSpec(**{**SPEC.__dict__, "hidden_dim": 160})
    with pytest.raises(ValueError, match="32-multiple"):
        shard_sim.synth_rank_q40(narrow, 2, scheme="fused")
    assert shard_sim.synth_rank_q40(narrow, 2, scheme="ref")  # ref: fine


def test_projection_itemization_consistent():
    from distributed_llama_tpu.parallel.comm_stats import ici_all_gather_bytes

    for scheme in ("ref", "fused"):
        proj = shard_sim.project_full_system(SPEC, 2, shard_ms=5.0,
                                             scheme=scheme)
        assert proj.ici_hidden_ms == 0  # serialized schemes: straight sum
        assert proj.total_ms == pytest.approx(
            proj.shard_ms + proj.ici_bandwidth_ms + proj.ici_latency_ms)
        assert proj.gather_bytes_per_chip == ici_all_gather_bytes(
            SPEC, 2, scheme).sent_bytes
    L = SPEC.n_layers
    ref = shard_sim.project_full_system(SPEC, 2, shard_ms=5.0, scheme="ref")
    fused = shard_sim.project_full_system(SPEC, 2, shard_ms=5.0,
                                          scheme="fused")
    assert ref.n_collectives == 4 * L + 1
    # the fused scheme's win: HALF the per-layer collective launches, so
    # the latency term (dominant on real shapes) halves too
    assert fused.n_collectives == 2 * L + 1
    assert fused.ici_latency_ms < ref.ici_latency_ms
    # Q80 buffers, ref scheme: byte total shrinks ~4x and the collective
    # COUNT is unchanged — codes + deltas ride ONE packed uint8 gather per
    # cut (tp._wire_gather, VERDICT r2 #4). Fused scheme: the combine
    # decomposes into scatter+gather pairs (count back to 4L+1) with the
    # packed payload on the gather half.
    spec80 = TransformerSpec(**{**SPEC.__dict__,
                                "buffer_float_type": FloatType.Q80})
    ref80 = shard_sim.project_full_system(spec80, 2, shard_ms=5.0,
                                          scheme="ref")
    fused80 = shard_sim.project_full_system(spec80, 2, shard_ms=5.0,
                                            scheme="fused")
    assert ref80.n_collectives == 4 * L + 1
    assert fused80.n_collectives == 4 * L + 1
    assert ref80.gather_bytes_per_chip < ref.gather_bytes_per_chip / 2
    assert ref80.ici_latency_ms == ref.ici_latency_ms
    # the north-star shape: ref 80 layers * 4 + logits = 321
    # collectives/token in both buffer modes; fused f32 drops to 161
    from distributed_llama_tpu.models.synth import llama2_70b_spec

    s70_80 = llama2_70b_spec(buffer_float_type=FloatType.Q80)
    assert shard_sim.project_full_system(
        s70_80, 8, shard_ms=16.5, scheme="ref").n_collectives == 321
    assert shard_sim.project_full_system(
        llama2_70b_spec(), 8, shard_ms=16.5,
        scheme="fused").n_collectives == 161


def test_overlap_projection_hides_collective_time():
    """The ISSUE 10 acceptance: at 13b-tp8 the overlap scheme's modeled
    ms/token is STRICTLY below the fused scheme's — the ring hops and
    the deferred ffn gathers hide behind compute (the
    max(compute_chunk, ring_hop) term), leaving roughly the attention
    gathers + logits gather exposed (~0.3 ms vs fused's 0.600)."""
    from distributed_llama_tpu.models.synth import llama2_13b_spec

    spec = llama2_13b_spec()
    shard_ms = 6.245  # the BENCH_r05 measured 13b-tp8 rank time
    fused = shard_sim.project_full_system(spec, 8, shard_ms,
                                          scheme="fused")
    over = shard_sim.project_full_system(spec, 8, shard_ms,
                                         scheme="overlap")
    assert over.scheme == "overlap" and over.ici_hidden_ms > 0
    # total subtracts the hidden share, never below the compute floor
    assert over.total_ms == pytest.approx(
        over.shard_ms + over.ici_bandwidth_ms + over.ici_latency_ms
        - over.ici_hidden_ms)
    assert over.total_ms > over.shard_ms
    assert over.total_ms < fused.total_ms
    # the exposed ICI remainder lands near the modeled floor: the L
    # attention gathers + the logits gather (~(L+1)*(S-1) hops)
    exposed = over.ici_bandwidth_ms + over.ici_latency_ms \
        - over.ici_hidden_ms
    L = spec.n_layers
    floor = (L + 1) * 7 * 1.0 / 1e3
    assert floor * 0.8 < exposed < floor * 1.5
    # the hidden share never exceeds what exists to hide
    assert over.ici_hidden_ms <= over.ici_bandwidth_ms \
        + over.ici_latency_ms
    # speculative composition keeps the hidden term
    sp = over.speculative(4, 0.7)
    assert sp.ms_per_accepted_token < fused.speculative(
        4, 0.7).ms_per_accepted_token


def test_overlap_rank_sim_band_shapes():
    """synth_rank_q40 under overlap = the fused band layout (the overlap
    scheme only changes the combine schedule, never the shards)."""
    over = shard_sim.synth_rank_q40(SPEC, 2, scheme="overlap")
    fused = shard_sim.synth_rank_q40(SPEC, 2, scheme="fused")
    assert over["wo"].logical_shape == fused["wo"].logical_shape
    assert over["w2"].logical_shape == fused["w2"].logical_shape
    narrow = TransformerSpec(**{**SPEC.__dict__, "hidden_dim": 160})
    with pytest.raises(ValueError, match="32-multiple"):
        shard_sim.synth_rank_q40(narrow, 2, scheme="overlap")


def test_rank_fused_q40_matches_dense(monkeypatch):
    """rank_params_to_device fuses the rank's wq/wk/wv (w1/w3) bands into
    wqkv/w13 kernel stacks; the fused Pallas rank program (interpret mode)
    must match the dense-weight rank program on the same values."""
    import jax.numpy as jnp

    from distributed_llama_tpu.io.loader import Q40Kernel, Q40KernelNb
    from distributed_llama_tpu.ops.linear import dequantize_weight

    bands = shard_sim.synth_rank_q40(SPEC, 2, seed=3)
    dense = {k: (np.asarray(dequantize_weight(v)) if hasattr(v, "qs") else v)
             for k, v in bands.items()}

    tokens = jnp.asarray([5, 9], jnp.int32)
    fwd = shard_sim.make_rank_forward(SPEC, 2)
    want, _ = fwd(shard_sim.rank_params_to_device(dense),
                  shard_sim.init_rank_cache(SPEC, 2), tokens, jnp.int32(0))

    monkeypatch.setenv("DLLAMA_Q40_KERNEL", "pallas")
    packed = shard_sim.rank_params_to_device(bands)
    assert isinstance(packed.get("wqkv"), Q40Kernel)  # fusion fired
    # w1/w3 bands (128, 64) pad 64x on the nb-minor layout, so the pad
    # gate re-tiles them nb-major before fusing
    assert isinstance(packed.get("w13"), (Q40Kernel, Q40KernelNb))
    got, _ = fwd(packed, shard_sim.init_rank_cache(SPEC, 2), tokens,
                 jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
