"""C++ host library vs the pure-Python/numpy reference implementations.

The native layer (csrc/host.cpp via ctypes) must be bit-identical to the
numpy codecs and the Python tokenizer/rng — it is an accelerated twin, not a
second implementation of the spec. Skips cleanly when no toolchain exists.
"""

import numpy as np
import pytest

from distributed_llama_tpu.utils import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


def test_xorshift_stream_parity():
    from distributed_llama_tpu.utils.rng import Xorshift64

    state, arr = native.xorshift_fill(800000010, 64, divisor=120.0)
    rng = Xorshift64(800000010)
    want = (rng.f32_array(64).astype(np.float64) / 120.0).astype(np.float32)
    np.testing.assert_array_equal(arr, want)
    assert state == rng.state


def test_q40_codec_roundtrip_parity():
    from distributed_llama_tpu.ops.quants import (pack_q40_bytes,
                                                  quantize_q40,
                                                  unpack_q40_bytes)

    x = (np.random.default_rng(3).standard_normal(4096) * 0.5).astype(
        np.float32)
    qs, d16 = quantize_q40(x)
    wire = np.frombuffer(pack_q40_bytes(qs, d16), dtype=np.uint8)

    dec = native.q40_decode_wire(wire, nb=4096 // 32)
    from distributed_llama_tpu.ops.quants import dequantize_q40

    np.testing.assert_array_equal(dec, dequantize_q40(qs, d16))


def test_native_q40_encode_matches_numpy():
    import ctypes

    lib = native._load()
    x = (np.random.default_rng(5).standard_normal(2048) * 0.7).astype(
        np.float32)
    out = np.empty((2048 // 32) * 18, dtype=np.uint8)
    lib.q40_encode(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                   2048 // 32)
    from distributed_llama_tpu.ops.quants import pack_q40_bytes, quantize_q40

    qs, d16 = quantize_q40(x)
    np.testing.assert_array_equal(
        out, np.frombuffer(pack_q40_bytes(qs, d16), dtype=np.uint8))


def test_native_q80_codec_matches_numpy():
    import ctypes

    lib = native._load()
    x = (np.random.default_rng(7).standard_normal(1024) * 2.0).astype(
        np.float32)
    out = np.empty((1024 // 32) * 34, dtype=np.uint8)
    lib.q80_encode(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                   1024 // 32)
    from distributed_llama_tpu.ops.quants import pack_q80_bytes, quantize_q80

    qs, d = quantize_q80(x)
    np.testing.assert_array_equal(
        out, np.frombuffer(pack_q80_bytes(qs, d), dtype=np.uint8))

    dec = np.empty(1024, dtype=np.float32)
    lib.q80_decode(out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                   dec.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   1024 // 32)
    from distributed_llama_tpu.ops.quants import dequantize_q80

    np.testing.assert_array_equal(dec, dequantize_q80(qs, d))


def test_native_bpe_matches_python(tmp_path):
    from distributed_llama_tpu.io.tokenizer import Tokenizer, write_tokenizer

    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    pieces += [b" ", b"h", b"i", b"s", b"t", b"hi", b" hi", b"is", b"this",
               b" this", b"hist"]
    scores = [0.0] * len(pieces)
    for p, s in [(b"hi", -1.0), (b" hi", -0.5), (b"is", -1.2), (b"this", -0.3),
                 (b" this", -0.2), (b"hist", -0.9)]:
        scores[pieces.index(p)] = s
    path = str(tmp_path / "tok.bin")
    write_tokenizer(path, pieces, scores)

    tok = Tokenizer(path, len(pieces))
    assert tok._native.available

    class _Off:
        available = False

    for text in ["hi", "this is history", "héllo ✨", "", "x" * 300]:
        native_ids = tok.encode(text)
        saved = tok._native
        tok._native = _Off()  # force the Python merge loop
        try:
            py_ids = tok.encode(text)
        finally:
            tok._native = saved
        assert native_ids == py_ids, text


def test_native_tile_kernel_layout_matches_numpy():
    from distributed_llama_tpu.utils import native

    if not native.available():
        import pytest

        pytest.skip("native library unavailable")
    rng = np.random.default_rng(6)
    qs = rng.integers(0, 256, (3, 40, 5, 16), dtype=np.uint8)
    d16 = (rng.random((3, 40, 5)) * 0.1).astype(np.float16)
    got = native.q40_tile_kernel_layout(qs, d16)
    assert got is not None
    qs_t, scale = got
    want_qs = np.ascontiguousarray(qs.transpose(0, 3, 1, 2))
    np.testing.assert_array_equal(qs_t, want_qs)
    np.testing.assert_array_equal(scale, d16.astype(np.float32))
    # unstacked rank-3 too
    qs_t2, scale2 = native.q40_tile_kernel_layout(qs[0], d16[0])
    np.testing.assert_array_equal(qs_t2, np.ascontiguousarray(
        qs[0].transpose(2, 0, 1)))
    np.testing.assert_array_equal(scale2, d16[0].astype(np.float32))


def test_native_sampler_matches_numpy():
    """csrc sample_logits vs the numpy Sampler path on identical
    logits/coins, across strategies (argmax is numpy-only; multinomial and
    nucleus exercise the native select)."""
    from distributed_llama_tpu.runtime.sampling import (sample_mult,
                                                        sample_topp,
                                                        softmax_f32)

    rng = np.random.default_rng(123)
    for case in range(200):
        n = int(rng.integers(4, 500))
        logits = (rng.standard_normal(n) * rng.uniform(0.5, 6)).astype(
            np.float32)
        temperature = float(rng.uniform(0.2, 1.5))
        coin = float(rng.uniform(0, 1))
        # nucleus (topp in (0,1)) and multinomial (topp outside)
        for topp in (float(rng.uniform(0.05, 0.99)), 1.0):
            got = native.sample_logits(logits, temperature, topp, coin)
            assert got is not None
            probs = softmax_f32(logits / np.float32(temperature))
            if topp <= 0 or topp >= 1:
                want = sample_mult(probs, coin)
            else:
                want = sample_topp(probs, topp, coin)
            assert got == want, (case, n, temperature, topp, coin)


def test_sampler_class_uses_native_consistently():
    """Sampler(use_native=True/False) must emit the same stream."""
    from distributed_llama_tpu.runtime.sampling import Sampler

    rng = np.random.default_rng(7)
    logits_seq = [rng.standard_normal(300).astype(np.float32) * 4
                  for _ in range(50)]
    a = Sampler(300, temperature=0.9, topp=0.9, seed=42, use_native=True)
    b = Sampler(300, temperature=0.9, topp=0.9, seed=42, use_native=False)
    for lg in logits_seq:
        assert a.sample(lg) == b.sample(lg)


def test_native_sampler_degenerate_nucleus():
    """topp < 1/n with near-uniform probs empties the cutoff pre-filter:
    both implementations must return the argmax, not crash/UB."""
    from distributed_llama_tpu.runtime.sampling import (sample_topp,
                                                        softmax_f32)

    n = 64
    logits = np.zeros(n, dtype=np.float32)
    logits[17] = 1e-4  # barely-top token
    for topp in (1e-6, 0.01):
        got = native.sample_logits(logits, 1.0, topp, 0.7)
        probs = softmax_f32(logits)
        want = sample_topp(probs, topp, 0.7)
        assert got == want == 17
    # n == 1: no (n-1) division
    one = np.zeros(1, dtype=np.float32)
    assert native.sample_logits(one, 1.0, 0.9, 0.3) == 0
    assert sample_topp(softmax_f32(one), 0.9, 0.3) == 0
