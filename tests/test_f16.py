"""F16 weight path end-to-end (the reference declares F16 — converter.py
supports it and funcs.cpp has matmulF16 — but ships no F16 models; here it is
a first-class weights-float-type)."""

import numpy as np

from distributed_llama_tpu.io.loader import load_model, write_model
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

BASE = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=300, seq_len=16)


def _tensors(spec, seed=3):
    rng = np.random.default_rng(seed)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.1).astype(np.float32)

    tensors = {"tok_embedding": t(spec.vocab_size, spec.dim),
               "rms_att": 1 + t(spec.n_layers, spec.dim),
               "rms_ffn": 1 + t(spec.n_layers, spec.dim),
               "rms_final": 1 + t(spec.dim),
               "wcls": t(spec.vocab_size, spec.dim)}
    for name, shape in spec.layer_matmul_shapes():
        tensors[name] = t(spec.n_layers, *shape)
    return tensors


def test_f16_write_load_forward_matches_f32(tmp_path):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)

    tensors = _tensors(BASE)
    spec16 = TransformerSpec(**{**BASE.__dict__,
                                "weights_float_type": FloatType.F16})
    p16 = str(tmp_path / "m16.bin")
    p32 = str(tmp_path / "m32.bin")
    write_model(p16, spec16, tensors)
    write_model(p32, BASE, tensors)
    assert spec16.file_size() < BASE.file_size()  # matmuls stored half-size

    s16, params16 = load_model(p16, weights_float_type=FloatType.F16)
    s32, params32 = load_model(p32, weights_float_type=FloatType.F32)
    assert params16["wq"].dtype == np.float16

    tokens = jnp.asarray([5, 9, 2], dtype=jnp.int32)
    lg16, _ = forward(s16, params_to_device(params16), init_cache(s16),
                      tokens, jnp.int32(0))
    lg32, _ = forward(s32, params_to_device(params32), init_cache(s32),
                      tokens, jnp.int32(0))
    # f16 storage rounds weights; activations/accumulation stay f32
    np.testing.assert_allclose(np.asarray(lg16), np.asarray(lg32),
                               rtol=0, atol=5e-3)
    diff = np.abs(np.asarray(lg16) - np.asarray(lg32)).max()
    assert diff > 0  # it genuinely went through the f16 storage path


def test_cli_f16_smoke(tmp_path, capsys):
    from distributed_llama_tpu.frontend.cli import main
    from distributed_llama_tpu.io.tokenizer import write_tokenizer

    spec16 = TransformerSpec(**{**BASE.__dict__,
                                "weights_float_type": FloatType.F16})
    model = str(tmp_path / "m16.bin")
    write_model(model, spec16, _tensors(BASE))
    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    while len(pieces) < BASE.vocab_size:
        pieces.append(f"tok{len(pieces)}".encode())
    tok = str(tmp_path / "tok.bin")
    write_tokenizer(tok, pieces, [0.0] * len(pieces))

    rc = main(["inference", "--model", model, "--tokenizer", tok,
               "--prompt", "a", "--steps", "3", "--temperature", "0",
               "--weights-float-type", "f16", "--tp", "1"])
    assert rc == 0
    assert "🔶" in capsys.readouterr().out
