"""Continuous batching: scheduling must be invisible in each request's
output — every request's token stream equals running it alone."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


@pytest.fixture(scope="module")
def params_dev(params):
    from distributed_llama_tpu.models.llama import params_to_device

    return params_to_device(params)


def test_forward_batch_ragged_matches_singles(params_dev):
    """Rows at DIFFERENT positions must each match the single-sequence
    forward at that position."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward,
                                                    forward_batch_ragged,
                                                    init_cache,
                                                    init_cache_batch)

    B = 3
    hists = {0: [7, 11, 5], 1: [17], 2: [40, 88]}  # row b is at pos len(b)
    tokens_now = jnp.asarray([9, 3, 77], dtype=jnp.int32)

    singles, caches = [], []
    for b in range(B):
        c = init_cache(SPEC)
        for p, t in enumerate(hists[b]):
            _, c = forward(SPEC, params_dev, c, jnp.asarray([t], jnp.int32),
                           jnp.int32(p))
        caches.append(c)
        lg, c2 = forward(SPEC, params_dev, c, tokens_now[b][None],
                         jnp.int32(len(hists[b])))
        singles.append((np.asarray(lg[0]), c2))

    cache_b = init_cache_batch(SPEC, B)._replace(
        k=jnp.stack([c.k for c in caches], axis=1),
        v=jnp.stack([c.v for c in caches], axis=1))
    pos_vec = jnp.asarray([len(hists[b]) for b in range(B)], jnp.int32)
    lg_b, cache_b2 = forward_batch_ragged(SPEC, params_dev, cache_b,
                                          tokens_now, pos_vec)
    for b in range(B):
        np.testing.assert_allclose(np.asarray(lg_b[b]), singles[b][0],
                                   rtol=2e-5, atol=2e-5)
        # the written cache column must land at each row's own position
        np.testing.assert_allclose(
            np.asarray(cache_b2.k[:, b, :len(hists[b]) + 1]),
            np.asarray(singles[b][1].k[:, :len(hists[b]) + 1]),
            rtol=1e-5, atol=1e-5)


def test_continuous_more_requests_than_slots(params, params_dev):
    """5 ragged requests through 2 slots, greedy: each output must equal the
    per-step reference loop's (generate()) output for that prompt alone."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import forward, init_cache
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    steps = 8
    reqs = [[1, 5, 9], [1, 22], [1, 7, 33, 2], [1, 60], [1, 90, 14]]

    # reference: plain single-sequence greedy decode per request
    singles = []
    for req in reqs:
        c = init_cache(SPEC)
        token, pos, out = req[0], 0, []
        while pos < steps:
            lg, c = forward(SPEC, params_dev, c,
                            jnp.asarray([token], jnp.int32), jnp.int32(pos))
            nxt = req[pos + 1] if pos + 1 < len(req) else int(
                np.argmax(np.asarray(lg[0])))
            pos += 1
            if nxt == 1:
                break
            out.append(nxt)
            token = nxt
        singles.append(out)

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                           seed=3)
    outs, stats = eng.run(reqs, steps)
    assert outs == singles
    assert stats.max_active <= 2
    # with 5 requests x 8 positions through 2 slots the scheduler must
    # actually overlap work (fewer steps than serial, more than one batch)
    assert steps <= stats.steps <= 5 * steps


@pytest.mark.parametrize("sp,tp", [(1, 2), (2, 1), (2, 2)])
def test_continuous_over_mesh_matches_single_chip(params, sp, tp):
    """The same request stream through an sp/tp sharded ragged step must be
    token-identical to the single-chip continuous engine (per-row position
    clocks through the sequence-chunked cache) — with and without sharded
    admission prefill."""
    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    steps = 8
    reqs = [[1, 5, 9], [1, 22], [1, 7, 33, 2]]
    ref_eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                               topp=0.9, seed=3)
    ref, _ = ref_eng.run(reqs, steps)

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                           seed=3, mesh=make_mesh(sp=sp, tp=tp))
    outs, _ = eng.run(reqs, steps)
    assert outs == ref

    eng_p = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                             topp=0.9, seed=3, mesh=make_mesh(sp=sp, tp=tp),
                             prefill_chunk=2)
    outs_p, stats_p = eng_p.run(reqs, steps)
    assert outs_p == ref
    # the prefilled rows skipped their prompt steps on the device
    assert stats_p.steps < eng.stats.steps


@pytest.mark.parametrize("temp,block,tp", [(0.0, 4, 1), (0.9, 4, 1),
                                           (0.9, 3, 1), (0.9, 4, 2)])
def test_continuous_block_steps_matches_per_step(params, temp, block, tp):
    """Fused K-step chains == per-step scheduling, token for token, across
    mixed prompts (more requests than slots, ragged lengths, budget and
    prompt retirements at non-boundary steps); the tp case runs the chain
    over the sharded batch step (the PARITY.md composition claim)."""
    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    steps = 10
    mesh = make_mesh(tp=tp) if tp > 1 else None
    reqs = [[1, 5, 9], [1, 22], [1, 7, 33, 2, 9, 14], [1, 60], [1, 90, 14]]
    ref, ref_stats = ContinuousEngine(SPEC, params, slots=2,
                                      temperature=temp, topp=0.9,
                                      seed=3).run(reqs, steps)
    got, _ = ContinuousEngine(SPEC, params, slots=2, temperature=temp,
                              topp=0.9, seed=3, mesh=mesh,
                              block_steps=block).run(reqs, steps)
    assert got == ref


def test_continuous_block_steps_per_request_overrides(params):
    """Per-request temperature/topp/seed ride through the fused chain (the
    traced-sampler path) identically to the per-step host sampler."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    def run_engine(block):
        eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                               topp=0.9, seed=5, block_steps=block)
        reqs = [Request(tokens=[1, 5, 9], steps=8, temperature=0.9,
                        topp=0.9, seed=11),
                Request(tokens=[1, 22], steps=8),  # greedy (engine default)
                Request(tokens=[1, 7, 33], steps=8, temperature=0.7,
                        topp=2.0, seed=13)]  # multinomial walk
        for r in reqs:
            eng.submit(r)
        while eng.step_many(block):
            pass
        return [r.out for r in reqs]

    assert run_engine(4) == run_engine(1)


def test_continuous_block_steps_with_prefill(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    steps = 10
    reqs = [[1, 5, 9, 14, 23, 40, 7, 11], [1, 22], [1, 7, 33, 2, 9]]
    ref, _ = ContinuousEngine(SPEC, params, slots=2, temperature=0.9,
                              topp=0.9, seed=3).run(reqs, steps)
    got, _ = ContinuousEngine(SPEC, params, slots=2, temperature=0.9,
                              topp=0.9, seed=3, prefill_chunk=4,
                              block_steps=4).run(reqs, steps)
    assert got == ref


@pytest.mark.parametrize("case_seed", [0, 1, 2])
def test_continuous_randomized_workloads_agree(params, case_seed):
    """Seeded fuzz: random ragged request mixes must produce identical
    per-request streams across every scheduler configuration (per-step,
    fused chains, prefill on/off) — the composition surface squared."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    rng = np.random.default_rng(1000 + case_seed)
    n_req = int(rng.integers(3, 7))
    reqs = []
    for _ in range(n_req):
        plen = int(rng.integers(1, 9))
        reqs.append([1] + list(rng.integers(3, SPEC.vocab_size - 1,
                                            plen - 1)))
    steps = int(rng.integers(4, SPEC.seq_len))
    slots = int(rng.integers(1, 4))
    temp = float(rng.choice([0.0, 0.9]))

    def outputs(**kw):
        return ContinuousEngine(SPEC, params, slots=slots, temperature=temp,
                                topp=0.9, seed=7, **kw).run(reqs, steps)[0]

    ref = outputs()
    assert outputs(block_steps=int(rng.integers(2, 6))) == ref
    assert outputs(prefill_chunk=int(rng.integers(2, 6))) == ref
    assert outputs(block_steps=4, prefill_chunk=3) == ref
    # everything at once: sharded step + fused chains + admission prefill
    from distributed_llama_tpu.parallel import make_mesh

    assert outputs(mesh=make_mesh(sp=2, tp=2), block_steps=3,
                   prefill_chunk=2) == ref


def test_continuous_bf16_cache_greedy_matches_f32(params):
    """--kv-cache-dtype bf16 through the continuous engine (per-row cache
    writes cast, fused chains, admission prefill): greedy streams on this
    tiny model should survive the cache rounding and match f32."""
    import jax.numpy as jnp

    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    steps = 8
    reqs = [[1, 5, 9], [1, 22], [1, 7, 33, 2]]
    ref, _ = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                              topp=0.9, seed=3).run(reqs, steps)
    got, _ = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                              topp=0.9, seed=3,
                              cache_dtype=jnp.bfloat16,
                              prefill_chunk=2, block_steps=4).run(reqs,
                                                                  steps)
    assert got == ref


def test_continuous_pos_never_reaches_seq_len(params):
    """A retired row's clock can hit seq_len; the freed slot must be parked
    back at pos 0 before the next device step — pos == seq_len reaching the
    flash kernel would DMA past the end of the cache row on TPU."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                           seed=3)
    seen = []
    real_step = eng._step

    def spy(params_, cache, tokens, pos_vec):
        seen.append(np.asarray(pos_vec).max())
        return real_step(params_, cache, tokens, pos_vec)

    eng._step = spy
    # steps == seq_len, desynced slots (one row retires early via its
    # shorter budget path while the other keeps going)
    reqs = [[1, 5, 9], [1, 22], [1, 7, 33, 2]]
    outs, _ = eng.run(reqs, steps=SPEC.seq_len)
    assert all(o is not None for o in outs)
    assert max(seen) < SPEC.seq_len


@pytest.mark.parametrize("temp", [0.0, 0.9])
def test_continuous_admission_prefill_matches_plain(params, temp):
    """prefill_chunk engine == step-by-step engine, token for token, across
    mixed prompt lengths (incl. one long enough for multiple chunks, one
    too short to engage prefill, and one longer than the budget)."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    steps = 10
    reqs = [[1, 5, 9, 14, 23, 40, 7, 11], [1, 22],
            [1] + list(range(20, 33)),  # 14 tokens: exceeds steps budget
            [1, 7, 33, 2, 9]]
    ref, ref_stats = ContinuousEngine(SPEC, params, slots=2,
                                      temperature=temp, topp=0.9,
                                      seed=3).run(reqs, steps)
    got, stats = ContinuousEngine(SPEC, params, slots=2, temperature=temp,
                                  topp=0.9, seed=3,
                                  prefill_chunk=4).run(reqs, steps)
    assert got == ref
    # the prefilled rows skipped their prompt steps on the device, but the
    # token count keeps its meaning across the toggle
    assert stats.steps < ref_stats.steps
    assert stats.tokens == ref_stats.tokens


def test_continuous_sampled_matches_generate(params):
    """Sampled decoding (temp>0): request i's stream == generate() run with
    the per-request seed — the scheduler must not disturb RNG consumption."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine
    from distributed_llama_tpu.runtime.generate import Engine, generate
    from distributed_llama_tpu.runtime.sampling import Sampler

    class _Tok:
        def encode(self, text, bos=True, eos=False):
            return [1] + [3 + b for b in text.encode()]

        def decode_piece(self, prev, tok):
            return b"?"

    steps, seed = 8, 41
    prompts = ["ab", "x", "hello"]
    tok = _Tok()

    singles = []
    for i, p in enumerate(prompts):
        eng = Engine(SPEC, params)
        sampler = Sampler(SPEC.vocab_size, temperature=0.9, topp=0.9,
                          seed=seed + i)
        out, _ = generate(eng, tok, sampler, p, steps, quiet=True)
        singles.append(out)

    ceng = ContinuousEngine(SPEC, params, slots=2, temperature=0.9, topp=0.9,
                            seed=seed)
    outs, _ = ceng.run([tok.encode(p) for p in prompts], steps)
    assert outs == singles


def test_use_native_sampler_plumbed_to_slots(params):
    """use_native_sampler=False (the multi-host pin, cli.py) must reach every
    admitted slot's Sampler — native and numpy can diverge by ulps across
    libm builds, so SPMD hosts must all take the numpy path (ADVICE r1)."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.9, topp=0.9,
                           seed=3, use_native_sampler=False)
    for r in ([1, 5], [1, 7]):
        eng.submit(Request(tokens=list(r), steps=4))
    eng._admit()
    samplers = [s.sampler for s in eng._pool if not s.free]
    assert samplers and all(s.use_native is False for s in samplers)
    # default stays native (single-host fast path)
    eng2 = ContinuousEngine(SPEC, params, slots=1, temperature=0.9, topp=0.9,
                            seed=3)
    eng2.submit(Request(tokens=[1, 5], steps=4))
    eng2._admit()
    assert eng2._pool[0].sampler.use_native is True
