"""device_params_like: on-device synthetic regeneration of a packed tree.

The bench path depends on two properties (BASELINE.md r3 warm start): the
regenerated tree must be structurally IDENTICAL to the host tree (shapes,
dtypes, treedef — the AOT decode loop compiles against these), and float
leaves must be small positive values (Q40 scales must be positive; no
inf/nan reachable downstream).
"""

import numpy as np

from distributed_llama_tpu.models.synth import (device_params_like,
                                                small_bench_spec,
                                                synth_q40_fast)
from distributed_llama_tpu.ops.linear import (fuse_q40_layer_matmuls,
                                              pack_q40_params)


def test_device_params_like_preserves_structure():
    import jax

    spec = small_bench_spec()
    host = fuse_q40_layer_matmuls(
        pack_q40_params(synth_q40_fast(spec), enable=True,
                        allow_nb_major=False))
    dev = device_params_like(host)
    h_leaves, h_def = jax.tree_util.tree_flatten(host)
    d_leaves, d_def = jax.tree_util.tree_flatten(dev)
    assert h_def == d_def
    for h, d in zip(h_leaves, d_leaves):
        assert tuple(h.shape) == tuple(d.shape)
        assert str(np.asarray(h).dtype) == str(d.dtype)
    for leaf in d_leaves:
        if str(leaf.dtype).startswith(("float", "bfloat")):
            a = np.asarray(leaf, dtype=np.float32)
            assert np.isfinite(a).all()
            assert a.min() > 0.0  # positive: the Q40 scale contract


def test_device_params_like_accepts_shape_structs():
    """The bench shape-manifest path feeds ShapeDtypeStructs, not arrays."""
    import jax

    spec = small_bench_spec()
    host = fuse_q40_layer_matmuls(
        pack_q40_params(synth_q40_fast(spec), enable=True,
                        allow_nb_major=False))
    sds = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.asarray(a).shape,
                                       np.asarray(a).dtype), host)
    dev = device_params_like(sds)
    for h, d in zip(jax.tree_util.tree_leaves(sds),
                    jax.tree_util.tree_leaves(dev)):
        assert tuple(h.shape) == tuple(d.shape)
        assert str(h.dtype) == str(d.dtype)
