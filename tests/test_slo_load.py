"""SLO observatory (ISSUE 8): slo.py verdict semantics, loadgen trace
determinism + record/replay, the virtual-clock engine driver (same seed +
same trace => identical schedule and verdict set), engine-threaded SLO
series, and the loadcheck CLI gate (including its seeded red paths)."""

import dataclasses
import json
import os
import sys

import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.obs.metrics import Registry
from distributed_llama_tpu.obs.slo import (SLOClass, SLOPolicy, SLOTracker,
                                           request_lifetimes)
from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                      Request)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=32)


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


# ------------------------------------------------------------ slo.py


def test_slo_class_evaluate_semantics():
    c = SLOClass("interactive", ttft_budget_s=1.0, token_budget_s=0.1)
    assert c.evaluate(0.5, 0.05) == "met"
    assert c.evaluate(1.0, 0.1) == "met"          # budgets are inclusive
    assert c.evaluate(1.5, 0.05) == "violated"    # TTFT over
    assert c.evaluate(0.5, 0.2) == "violated"     # per-token over
    assert c.evaluate(None, None) == "met"        # unreached phases
    assert c.evaluate(0.1, 0.01, failed=True) == "failed"


def test_slo_class_rejects_bad_specs():
    with pytest.raises(ValueError):
        SLOClass("x", 0.0, 1.0)
    with pytest.raises(ValueError):
        SLOClass("x", 1.0, -1.0)
    with pytest.raises(ValueError):
        SLOClass('a"b', 1.0, 1.0)
    with pytest.raises(ValueError):
        SLOClass("", 1.0, 1.0)


def test_slo_policy_parse_resolve_and_errors():
    p = SLOPolicy.parse("interactive:1000:100,batch:60000:5000")
    assert p.default_class == "interactive"
    assert p.resolve(None).name == "interactive"
    assert p.resolve("batch").ttft_budget_s == pytest.approx(60.0)
    assert p.resolve("batch").token_budget_s == pytest.approx(5.0)
    with pytest.raises(ValueError):
        p.resolve("nope")
    with pytest.raises(ValueError):
        SLOPolicy.parse("interactive:1000")  # missing a field
    with pytest.raises(ValueError):
        SLOPolicy.parse("a:1:1,a:2:2")       # duplicate names
    assert SLOPolicy.serving_default().names == ("interactive", "batch")


def test_slo_tracker_counts_goodput_and_series():
    reg = Registry()
    p = SLOPolicy((SLOClass("fast", 1.0, 0.1), SLOClass("slow", 10.0, 1.0)))
    t = SLOTracker(p, reg)
    assert t.observe("fast", 0.5, 0.05, tokens=10) == "met"
    assert t.observe("fast", 5.0, 0.05, tokens=10) == "violated"
    assert t.observe(None, 0.5, 0.05, tokens=4) == "met"  # default class
    assert t.observe("slow", None, None, tokens=0, failed=True) == "failed"
    snap = t.snapshot()
    fast = snap["classes"]["fast"]
    assert fast["attempted"] == 3 and fast["met"] == 2
    assert fast["violated"] == 1 and fast["goodput_tokens"] == 14
    assert fast["attainment"] == pytest.approx(2 / 3, abs=1e-4)
    assert snap["classes"]["slow"]["failed"] == 1
    assert snap["goodput_tokens_total"] == 14
    # labeled series mirror the tallies, full matrix pre-registered at 0
    assert reg.get('dllama_slo_requests_total'
                   '{class="fast",verdict="met"}').value == 2
    assert reg.get('dllama_slo_requests_total'
                   '{class="fast",verdict="violated"}').value == 1
    assert reg.get('dllama_slo_requests_total'
                   '{class="slow",verdict="failed"}').value == 1
    assert reg.get('dllama_slo_requests_total'
                   '{class="slow",verdict="met"}').value == 0
    assert reg.get('dllama_goodput_tokens_total{class="fast"}').value == 14
    text = reg.expose()
    assert "# TYPE dllama_slo_requests_total counter" in text
    assert text.count("# TYPE dllama_slo_requests_total") == 1


def test_request_lifetimes_decomposition():
    req = Request(tokens=[1, 5], steps=4)
    req.t_enqueue, req.t_first_token, req.n_sampled = 10.0, 12.0, 4
    ttft, per_token = request_lifetimes(req, now=14.0)
    assert ttft == pytest.approx(2.0)
    assert per_token == pytest.approx(0.5)
    req2 = Request(tokens=[1, 5], steps=4)
    req2.t_enqueue = 10.0  # never sampled
    assert request_lifetimes(req2, now=14.0) == (None, None)


def test_engine_threads_slo_verdicts_through_retire(params):
    """The tentpole wiring: verdicts land per class at retire, goodput
    counts only met requests, cancelled requests are excluded."""
    reg = Registry()
    policy = SLOPolicy((SLOClass("lax", 1e6, 1e6),
                        SLOClass("strict", 1e-9, 1e-9)))
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                           topp=0.9, seed=5, metrics=reg, slo=policy)
    lax = Request(tokens=[1, 5, 9], steps=8, slo_class="lax")
    strict = Request(tokens=[1, 7, 11], steps=8, slo_class="strict")
    ghost = Request(tokens=[1, 13], steps=8, slo_class="lax")
    for r in (lax, strict, ghost):
        eng.submit(r)
    ghost.cancelled = True
    while eng.step_once():
        pass
    snap = eng.slo_tracker.snapshot()
    assert snap["classes"]["lax"]["met"] == 1
    assert snap["classes"]["strict"]["violated"] == 1
    assert snap["classes"]["lax"]["attempted"] == 1  # cancelled excluded
    assert snap["classes"]["lax"]["goodput_tokens"] == lax.n_sampled > 0
    assert snap["classes"]["strict"]["goodput_tokens"] == 0
    assert reg.get('dllama_slo_requests_total'
                   '{class="strict",verdict="violated"}').value == 1
    assert reg.get('dllama_goodput_tokens_total'
                   '{class="lax"}').value == lax.n_sampled


def test_engine_fail_all_records_failed_verdicts(params):
    policy = SLOPolicy((SLOClass("c", 1e6, 1e6),))
    eng = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                           topp=0.9, seed=5, slo=policy)
    eng.submit(Request(tokens=[1, 5], steps=8))
    eng.submit(Request(tokens=[1, 7], steps=8))
    eng.step_once()
    eng.fail_all("injected")
    snap = eng.slo_tracker.snapshot()
    assert snap["classes"]["c"]["failed"] == 2


# ------------------------------------------------------------ loadgen


def _spec(**kw):
    from loadgen import LoadSpec

    base = dict(rate=0.3, n_requests=16, arrivals="bursty",
                prompt_lens=(3, 5, 8), out_lens=(4, 8),
                shared_prefix_rate=0.5, shared_prefix_len=8,
                n_shared_prefixes=2, classes=("a", "b"),
                class_weights=(3, 1), vocab=SPEC.vocab_size,
                seq_len=SPEC.seq_len)
    base.update(kw)
    return LoadSpec(**base)


def test_trace_generation_is_deterministic_and_well_formed():
    from loadgen import BOS, generate_trace

    t1 = generate_trace(_spec(), seed=11)
    t2 = generate_trace(_spec(), seed=11)
    assert t1.events == t2.events
    assert generate_trace(_spec(), seed=12).events != t1.events
    last = 0.0
    for e in t1.events:
        assert e.t >= last  # arrivals are ordered
        last = e.t
        assert e.tokens[0] == BOS and BOS not in e.tokens[1:]
        assert all(3 <= tok < SPEC.vocab_size for tok in e.tokens[1:])
        assert e.steps <= SPEC.seq_len
        assert e.slo_class in ("a", "b")
    assert t1.offered_rate > 0


def test_trace_arrival_processes_differ_and_prefixes_shared():
    from loadgen import generate_trace

    poisson = generate_trace(_spec(arrivals="poisson"), seed=11)
    bursty = generate_trace(_spec(arrivals="bursty"), seed=11)
    assert [e.t for e in poisson.events] != [e.t for e in bursty.events]
    # the shared-prefix mix produces repeated page-aligned openings
    t = generate_trace(_spec(n_requests=32), seed=3)
    openings = [e.tokens[1:9] for e in t.events if len(e.tokens) >= 9]
    shared = [o for o in openings if openings.count(o) > 1]
    assert shared, "no request shared a system-prompt opening"


def test_trace_save_load_round_trip(tmp_path):
    from loadgen import generate_trace, load_trace, save_trace

    trace = generate_trace(_spec(), seed=11)
    path = str(tmp_path / "trace.json")
    save_trace(trace, path)
    back = load_trace(path)
    assert back.events == trace.events
    assert back.seed == trace.seed
    with pytest.raises(ValueError):
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as fh:
            json.dump({"kind": "nope"}, fh)
        load_trace(bad)


def _policy():
    return SLOPolicy((SLOClass("a", 12.0, 3.0), SLOClass("b", 120.0, 30.0)))


def _engine(params, **kw):
    base = dict(slots=4, temperature=0.0, topp=0.9, seed=7,
                prefill_chunk=4, page_size=4, kv_pages=20)
    base.update(kw)
    return ContinuousEngine(SPEC, params, **base)


def test_drive_engine_determinism_same_seed_same_verdicts(params, tmp_path):
    """THE determinism satellite: same seed + same trace file => identical
    arrival schedule and identical per-request verdict set across two
    runs (engine-level, CPU, small model)."""
    from loadgen import (drive_engine, generate_trace, load_trace,
                         save_trace)

    spec = _spec(rate=0.8, n_requests=20)  # past the knee: mixed verdicts
    trace = generate_trace(spec, seed=11)
    path = str(tmp_path / "trace.json")
    save_trace(trace, path)
    replay = load_trace(path)
    assert [e.t for e in replay.events] == [e.t for e in trace.events]

    r1 = drive_engine(_engine(params), trace, _policy())
    r2 = drive_engine(_engine(params), replay, _policy())
    assert r1.verdicts() == r2.verdicts()
    assert [r.ttft for r in r1.records] == [r.ttft for r in r2.records]
    assert r1.goodput_tokens == r2.goodput_tokens
    assert r1.duration == r2.duration
    # the point is non-trivial: the verdict set must contain a mix
    kinds = {v for _, _, v in r1.verdicts()}
    assert "met" in kinds and "violated" in kinds
    # every request resolved, engine drained clean
    assert all(r.v_finish is not None for r in r1.records)


def test_drive_engine_attainment_and_goodput_math(params):
    from loadgen import drive_engine, generate_trace

    res = drive_engine(_engine(params),
                       generate_trace(_spec(rate=0.05), seed=11),
                       _policy())
    # unloaded: everything met, goodput == all sampled tokens
    assert res.attainment == {"a": 1.0, "b": 1.0}
    assert res.goodput_tokens == sum(r.n_sampled for r in res.records)
    assert res.goodput_tps == pytest.approx(
        res.goodput_tokens / res.duration)
    row = res.to_json()
    assert row["attainment"]["a"] == 1.0
    assert row["engine"]["steps"] > 0


# ----------------------------------------------------------- loadcheck


def _run_loadcheck(argv, capsys):
    import loadcheck

    rc = loadcheck.main(argv)
    out = capsys.readouterr().out.strip().splitlines()
    return rc, json.loads(out[-1])


def test_loadcheck_sweep_curve_and_baseline_gate(params, tmp_path, capsys):
    base = str(tmp_path / "baseline.json")
    quick = ["--sweep", "0.1,0.2,0.4,0.8", "--requests", "8",
             "--sweep-only", "--baseline", base, "--json"]
    rc, row = _run_loadcheck(quick + ["--write-baseline"], capsys)
    assert rc == 0
    assert len(row["sweep"]) >= 4                      # a curve, not a dot
    assert row["gate"]["verdict"] == "OK"
    # the row is stamped: fingerprint + the active engine config
    assert "env_fingerprint" in row and "tp_scheme" in row
    for key in ("page_size", "kv_pages", "spec_k", "slots", "seed"):
        assert key in row["config"]
    for point in row["sweep"]:
        assert {"rate", "goodput_tps", "attainment",
                "token_p99"} <= set(point)
    # replay against the freshly written band: in-band, exit 0
    rc2, row2 = _run_loadcheck(quick, capsys)
    assert rc2 == 0
    assert row2["sweep"] == row["sweep"]  # deterministic curve
    # tamper the band: the same run must now be a RED regression
    with open(base) as fh:
        doc = json.load(fh)
    for p in doc["points"]:
        p["band"] = [p["band"][1] * 10, p["band"][1] * 20]
    with open(base, "w") as fh:
        json.dump(doc, fh)
    rc3, row3 = _run_loadcheck(quick, capsys)
    assert rc3 == 1
    assert row3["gate"]["verdict"] == "RED"
    assert any("regression" in f for f in row3["gate"]["failures"])


def test_loadcheck_drills_green_and_leak_mutation_red(capsys):
    rc, row = _run_loadcheck(
        ["--drills-only", "--drills", "disconnect,transient_starvation",
         "--json"], capsys)
    assert rc == 0
    assert {d["name"] for d in row["drills"]} == {"disconnect",
                                                  "transient_starvation"}
    assert all(d["passed"] for d in row["drills"])
    rc, row = _run_loadcheck(
        ["--drills-only", "--drills", "disconnect", "--inject",
         "leak-on-cancel", "--json"], capsys)
    assert rc == 1
    assert row["gate"]["verdict"] == "RED"
    assert not row["drills"][0]["passed"]


def test_loadcheck_usage_errors(capsys):
    import loadcheck

    assert loadcheck.main(["--sweep", "0.1,0.2", "--sweep-only"]) == 2
    assert loadcheck.main(["--sweep", "abc"]) == 2
    assert loadcheck.main(["--sweep-only", "--drills-only"]) == 2
    capsys.readouterr()


def test_checked_in_baseline_matches_current_curve(capsys):
    """The CPU band in tools/loadcheck_baseline.json must hold for the
    default sweep — the same gate ci.sh runs (kept in tier-1 so a
    scheduling change that shifts goodput shows up here, not in CI)."""
    rc, row = _run_loadcheck(["--sweep-only", "--json"], capsys)
    assert rc == 0, row["gate"]["failures"]
    # the default sweep reaches saturation: attainment degrades at the
    # top rate while the low rates attain fully (the knee is visible)
    sweep = row["sweep"]
    assert sweep[0]["attainment"]["interactive"] == 1.0
    assert sweep[-1]["attainment"]["interactive"] < 1.0


# --------------------------------------------------- server /health slo


def test_server_health_slo_block_and_class_routing(params):
    import urllib.error
    import urllib.request

    from distributed_llama_tpu.runtime.server import InferenceServer

    class _IdTok:
        def encode(self, text, bos=True, eos=False):
            return [1] + [3 + b for b in text.encode()]

        def decode_piece(self, prev, tok):
            return b"<%d>" % tok

    policy = SLOPolicy((SLOClass("lax", 1e6, 1e6),
                        SLOClass("strict", 1e-9, 1e-9)))
    srv = InferenceServer(SPEC, params, _IdTok(), "127.0.0.1", 0,
                          slots=2, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, slo=policy)
    srv.start()
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())

        post({"prompt": "ab", "steps": 8})                    # default: lax
        post({"prompt": "cd", "steps": 8, "class": "strict"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            post({"prompt": "x", "steps": 8, "class": "nope"})
        assert ei.value.code == 400
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/health", timeout=30) as r:
            h = json.loads(r.read())
        assert h["slo"]["classes"]["lax"]["met"] == 1
        assert h["slo"]["classes"]["strict"]["violated"] == 1
        assert "queue_depth" in h and "pauses" in h
        assert h["admission_rejected"]["bad_request"] == 1
    finally:
        srv.stop()


# ------------------------------------------------------- HTTP driver


def test_drive_http_against_live_server(params):
    """The wall-clock driver end to end: generous budgets on an unloaded
    server => every request met, token counts non-zero."""
    from distributed_llama_tpu.runtime.server import InferenceServer
    from loadgen import drive_http, generate_trace

    class _IdTok:
        def encode(self, text, bos=True, eos=False):
            return [1] + [3 + b for b in text.encode()]

        def decode_piece(self, prev, tok):
            return b"<%d>" % tok

    policy = SLOPolicy((SLOClass("a", 60.0, 30.0),))
    srv = InferenceServer(SPEC, params, _IdTok(), "127.0.0.1", 0,
                          slots=4, steps=8, temperature=0.0, topp=0.9,
                          seed=5, quiet=True, slo=policy)
    srv.start()
    try:
        trace = generate_trace(
            _spec(rate=5.0, n_requests=6, shared_prefix_rate=0.0,
                  classes=("a",), class_weights=()), seed=11)
        res = drive_http(f"http://127.0.0.1:{srv.port}", trace, policy,
                         time_scale=0.01)
        assert len(res.records) == 6
        assert all(r.error is None for r in res.records), \
            [r.error for r in res.records]
        # the server-side tracker saw the same six requests
        assert srv.engine.slo_tracker.snapshot()[
            "classes"]["a"]["attempted"] == 6
        assert all(r.tokens_out > 0 for r in res.records)
        assert res.attainment == {"a": 1.0}
    finally:
        srv.stop()


def test_load_spec_validation():
    from loadgen import LoadSpec

    with pytest.raises(ValueError):
        LoadSpec(arrivals="weird")
    with pytest.raises(ValueError):
        LoadSpec(rate=0.0)
    with pytest.raises(ValueError):
        LoadSpec(shared_prefix_rate=0.5, shared_prefix_len=0)
    assert dataclasses.asdict(LoadSpec())["rate"] == 0.25
