"""Sequence-parallel training (ring attention) vs the dense training step."""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params

SPEC = TransformerSpec(dim=64, hidden_dim=96, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=64)


@pytest.fixture(scope="module")
def setup():
    import jax.numpy as jnp

    from distributed_llama_tpu.parallel import make_mesh

    params = synth_params(SPEC, q40=False, seed=8, scale=0.15)
    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, SPEC.vocab_size, (4, 17)),
                         dtype=jnp.int32)  # T = 16 splits over sp=4
    return params, tokens, make_mesh


def test_sp_train_loss_matches_dense(setup):
    params, tokens, make_mesh = setup

    from distributed_llama_tpu.parallel.sp_train import make_sp_train_step
    from distributed_llama_tpu.parallel.train import make_train_step

    # dense reference on a dp x tp mesh (no sp)
    mesh_ref = make_mesh(dp=2, tp=1)
    init_ref, step_ref = make_train_step(SPEC, mesh_ref, learning_rate=1e-3)
    p_ref, o_ref = init_ref(params)
    p_ref, o_ref, loss_ref = step_ref(p_ref, o_ref, tokens)

    mesh_sp = make_mesh(dp=2, sp=4, tp=1)
    init_sp, step_sp = make_sp_train_step(SPEC, mesh_sp, learning_rate=1e-3)
    p_sp, o_sp = init_sp(params)
    p_sp, o_sp, loss_sp = step_sp(p_sp, o_sp, tokens)

    np.testing.assert_allclose(float(loss_sp), float(loss_ref),
                               rtol=1e-5, atol=1e-5)
    # gradients flowed through the ppermute ring identically: the updated
    # params agree with the dense step's
    for k in ("wq", "w1", "tok_embedding"):
        np.testing.assert_allclose(np.asarray(p_sp[k]), np.asarray(p_ref[k]),
                                   rtol=2e-4, atol=2e-4)


def test_sp_train_loss_decreases(setup):
    params, tokens, make_mesh = setup

    from distributed_llama_tpu.parallel.sp_train import make_sp_train_step

    mesh = make_mesh(dp=1, sp=2, tp=1)
    init_fn, step_fn = make_sp_train_step(SPEC, mesh, learning_rate=5e-3)
    p, o = init_fn(params)
    losses = []
    for _ in range(4):
        p, o, loss = step_fn(p, o, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
