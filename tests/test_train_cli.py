"""`train` CLI mode: loss goes down, and a checkpointed split run
reproduces the unsplit run's losses step for step."""

import re

import numpy as np
import pytest

from distributed_llama_tpu.io.loader import write_model
from distributed_llama_tpu.io.tokenizer import write_tokenizer
from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.ops.quants import FloatType

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=300, seq_len=32)


@pytest.fixture(scope="module")
def files(tmp_path_factory):
    d = tmp_path_factory.mktemp("t")
    rng = np.random.default_rng(5)

    def t(*shape):
        return (rng.standard_normal(shape) * 0.1).astype(np.float32)

    tensors = {"tok_embedding": t(SPEC.vocab_size, SPEC.dim),
               "rms_att": 1 + t(SPEC.n_layers, SPEC.dim),
               "rms_ffn": 1 + t(SPEC.n_layers, SPEC.dim),
               "rms_final": 1 + t(SPEC.dim),
               "wcls": t(SPEC.vocab_size, SPEC.dim)}
    for name, shape in SPEC.layer_matmul_shapes():
        tensors[name] = t(SPEC.n_layers, *shape)
    f32 = str(d / "m32.bin")
    write_model(f32, SPEC, tensors)
    import dataclasses

    q40_spec = dataclasses.replace(SPEC, weights_float_type=FloatType.Q40)
    q40 = str(d / "m40.bin")
    write_model(q40, q40_spec, tensors)

    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    while len(pieces) < SPEC.vocab_size:
        pieces.append(f"tok{len(pieces)}".encode())
    tok = str(d / "tok.bin")
    write_tokenizer(tok, pieces, [0.0] * len(pieces))

    data = str(d / "corpus.txt")
    with open(data, "w") as fh:
        fh.write("the quick brown fox jumps over the lazy dog " * 40)
    return f32, q40, tok, data


def _losses(out: str) -> list[float]:
    return [float(m.group(1))
            for m in re.finditer(r"loss\s+([0-9.]+)", out)]


def test_train_cli_loss_decreases(files, capsys):
    from distributed_llama_tpu.frontend.cli import main

    f32, _, tok, data = files
    assert main(["train", "--model", f32, "--tokenizer", tok,
                 "--data", data, "--steps", "6", "--batch", "4",
                 "--seq", "16", "--learning-rate", "3e-3",
                 "--dp", "2", "--tp", "2"]) == 0
    losses = _losses(capsys.readouterr().out)
    assert len(losses) == 6
    assert losses[-1] < losses[0]


def test_train_cli_split_resume_matches_unsplit(files, tmp_path, capsys):
    from distributed_llama_tpu.frontend.cli import main

    f32, _, tok, data = files
    base = ["--model", f32, "--tokenizer", tok, "--data", data,
            "--batch", "2", "--seq", "16", "--learning-rate", "3e-3",
            "--seed", "3", "--dp", "1", "--tp", "2"]
    assert main(["train", *base, "--steps", "4"]) == 0
    full = _losses(capsys.readouterr().out)

    ck = str(tmp_path / "t.ckpt")
    assert main(["train", *base, "--steps", "2", "--save-state", ck]) == 0
    part1 = _losses(capsys.readouterr().out)
    assert main(["train", *base, "--steps", "2",
                 "--resume-state", ck]) == 0
    out2 = capsys.readouterr().out
    assert "Resumed training at step 2" in out2
    part2 = _losses(out2)
    np.testing.assert_allclose(part1 + part2, full, rtol=1e-6)

    # resuming with a different --seed would silently change the data
    # schedule: refused (the checkpoint records the seed)
    wrong = [a if a != "3" else "4" for a in base]
    assert main(["train", *wrong, "--steps", "2",
                 "--resume-state", ck]) == 2


def test_train_cli_densifies_q40(files, capsys):
    """A Q40 model file trains after densification (the codec value map)."""
    from distributed_llama_tpu.frontend.cli import main

    _, q40, tok, data = files
    assert main(["train", "--model", q40, "--tokenizer", tok,
                 "--data", data, "--weights-float-type", "q40",
                 "--steps", "3", "--batch", "2", "--seq", "8",
                 "--learning-rate", "3e-3"]) == 0
    losses = _losses(capsys.readouterr().out)
    assert len(losses) == 3 and np.isfinite(losses).all()


def test_train_cli_rejects_bad_seq(files, capsys):
    from distributed_llama_tpu.frontend.cli import main

    f32, _, tok, data = files
    assert main(["train", "--model", f32, "--tokenizer", tok,
                 "--data", data, "--seq", str(SPEC.seq_len)]) == 2


def test_train_cli_rejects_tiny_corpus(files, tmp_path, capsys):
    """A corpus too short for one (seq+1)-token window is refused before any
    weight streaming."""
    from distributed_llama_tpu.frontend.cli import main

    f32, _, tok, _ = files
    tiny = str(tmp_path / "tiny.txt")
    with open(tiny, "w") as fh:
        fh.write("hi")
    assert main(["train", "--model", f32, "--tokenizer", tok,
                 "--data", tiny, "--seq", "16"]) == 2
