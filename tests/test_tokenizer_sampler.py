"""Tokenizer + sampler behavior tests (reference src/tokenizer.cpp)."""

import numpy as np
import pytest

from distributed_llama_tpu.io.tokenizer import BOS, EOS, Tokenizer, write_tokenizer
from distributed_llama_tpu.runtime.sampling import (Sampler, sample_mult,
                                                    sample_topp, softmax_f32)


@pytest.fixture()
def tok(tmp_path):
    # vocab: 0..2 specials, 3..258 byte tokens, then text pieces
    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    extra = [(b" ", -1.0), (b"a", -2.0), (b"b", -3.0), (b"ab", -0.5),
             (b" a", -0.6), (b"c", -4.0), (b"abc", -0.1)]
    scores = [0.0] * len(pieces) + [s for _, s in extra]
    pieces += [p for p, _ in extra]
    path = str(tmp_path / "tok.bin")
    write_tokenizer(path, pieces, scores)
    return Tokenizer(path, len(pieces))


def test_encode_merges_best_pair_first(tok):
    # "abc": a+b -> "ab" (score -0.5) ... then ab+c -> "abc" (score -0.1)
    ids = tok.encode("abc", bos=True, eos=False)
    assert ids[0] == BOS
    assert tok.vocab[ids[1]] == b" a" or tok.vocab[ids[1]] == b" "
    # final sequence decodes back to " abc" minus the BOS-stripped space
    assert tok.decode(ids[1:]) in (b" abc", b"abc")


def test_encode_dummy_prefix_and_empty(tok):
    assert tok.encode("", bos=True, eos=False) == [BOS]
    ids = tok.encode("a", bos=True, eos=True)
    assert ids[0] == BOS and ids[-1] == EOS
    # dummy prefix " " merges with "a" into " a" (score -0.6 beats others)
    assert tok.vocab[ids[1]] == b" a"


def test_byte_fallback(tok):
    # "z" is not in vocab -> byte token z+3
    ids = tok.encode("z", bos=False, eos=False)
    assert ids[-1] == ord("z") + 3
    assert tok.decode_piece(0, ids[-1]) == b"z"


def test_utf8_multibyte_fallback(tok):
    text = "é"  # 2 bytes, not in vocab -> two byte tokens
    ids = tok.encode(text, bos=False, eos=False)
    bs = text.encode("utf-8")
    assert ids[-2:] == [bs[0] + 3, bs[1] + 3]
    assert tok.decode(ids)[-2:] == bs


def test_decode_strips_space_after_bos(tok):
    sp = tok.vocab.index(b" a")
    assert tok.decode_piece(BOS, sp) == b"a"
    assert tok.decode_piece(5, sp) == b" a"


def test_sampler_argmax():
    s = Sampler(8, temperature=0.0, topp=0.9, seed=1)
    logits = np.array([0.1, 3.0, -1, 0, 0, 0, 0, 2.9], np.float32)
    assert s.sample(logits) == 1


def test_sampler_deterministic_seed():
    logits = np.random.default_rng(0).standard_normal(64).astype(np.float32)
    a = [Sampler(64, 0.8, 0.9, seed=42).sample(logits) for _ in range(3)]
    b = [Sampler(64, 0.8, 0.9, seed=42).sample(logits) for _ in range(3)]
    assert a == b
    # different seeds eventually differ
    outs = {Sampler(64, 0.8, 0.9, seed=s).sample(logits) for s in range(20)}
    assert len(outs) > 1


def test_sample_mult_cdf_walk():
    probs = np.array([0.1, 0.2, 0.3, 0.4], np.float32)
    assert sample_mult(probs, 0.05) == 0
    assert sample_mult(probs, 0.25) == 1
    assert sample_mult(probs, 0.999) == 3
    assert sample_mult(probs, 1.5) == 3  # rounding-error guard


def test_sample_topp_truncates_tail():
    # p = [0.5, 0.3, 0.1, 0.1], topp=0.7 -> nucleus {0, 1}
    probs = np.array([0.5, 0.3, 0.1, 0.1], np.float32)
    picks = {sample_topp(probs, 0.7, coin) for coin in
             (0.01, 0.3, 0.6, 0.95)}
    assert picks <= {0, 1}


def test_sampler_temperature_sharpens():
    logits = np.array([1.0, 1.1, 0.9, 5.0], np.float32)
    picks = [Sampler(4, 0.01, 0.0, seed=s).sample(logits.copy())
             for s in range(10)]
    assert all(p == 3 for p in picks)


def test_softmax_f32_matches_reference_shape():
    x = np.array([1.0, 2.0, 3.0], np.float32)
    p = softmax_f32(x)
    assert abs(p.sum() - 1.0) < 1e-6 and p.argmax() == 2
