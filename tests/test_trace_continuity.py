"""Trace continuity across the three process seams (ISSUE 15).

The acceptance contract: a request keeps ONE trace_id across (a) the
prefill->decode disaggregation handoff over two REAL HTTP servers —
whose stitched exports tracejoin must join with zero orphans and the
handoff span bridging both pools — (b) journal recovery after a crash,
and (c) the kill-mid-handoff combination, with the continuation link
span present at every seam. The subprocess-SIGKILL variants of (b)/(c)
live in runtime/chaos.py's drills (slow-marked + ci.sh); here the crash
is simulated by abandoning the first engine on a settled journal — the
journal bytes are identical to what a SIGKILL leaves."""

import json
import os
import sys
import urllib.request

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

from distributed_llama_tpu.models.spec import TransformerSpec  # noqa: E402
from distributed_llama_tpu.models.synth import synth_params  # noqa: E402
from distributed_llama_tpu.obs import tracectx  # noqa: E402
from distributed_llama_tpu.obs.metrics import Registry  # noqa: E402
from distributed_llama_tpu.runtime.continuous import (  # noqa: E402
    ContinuousEngine, Request)
from distributed_llama_tpu.runtime.journal import RequestJournal  # noqa: E402

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=32)


class _IdTokenizer:
    def encode(self, text, bos=True, eos=False):
        return [1] + [3 + b for b in text.encode()]

    def decode_piece(self, prev, tok):
        return b"<%d>" % tok


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


def make_engine(params, journal=None, remote=False, **kw):
    base = dict(slots=2, temperature=0.0, topp=0.9, seed=11,
                prefill_chunk=4, page_size=4, kv_pages=24,
                metrics=Registry())
    base.update(kw)
    return ContinuousEngine(SPEC, params, journal=journal,
                            remote_pages=remote, **base)


def _drain(eng):
    while eng.step_many(eng.block_steps, quiet=True):
        pass


# ------------------------------------------------ seam 1: journal recovery


def test_recovery_continues_trace_with_link(params, tmp_path):
    """A recovered request keeps its journaled trace_id, its new span
    parents on the journaled span, the 'recovers' link span lands in
    the timeline, and the NEW admit record re-journals the continued
    identity (a second crash continues the same trace again)."""
    jpath = str(tmp_path / "requests.journal")
    eng = ContinuousEngine(SPEC, params, slots=1, temperature=0.8,
                           topp=0.9, seed=11, prefill_chunk=4,
                           page_size=4, kv_pages=24, metrics=Registry(),
                           journal=RequestJournal(jpath))
    req = Request(tokens=[1, 9, 17, 25], steps=20, temperature=0.9,
                  seed=501)
    eng.submit(req)
    root = req.trace
    assert root is not None and root.link is None
    for _ in range(4):  # mid-decode, tokens journaled
        eng.step_many(1, quiet=True)
    eng._journal.sync(force=True)
    eng._journal._fh.close()  # the simulated SIGKILL

    journal2 = RequestJournal(jpath)
    (entry,) = journal2.incomplete()
    assert entry.trace == root.to_header()
    eng2 = ContinuousEngine(SPEC, params, slots=1, temperature=0.8,
                            topp=0.9, seed=11, prefill_chunk=4,
                            page_size=4, kv_pages=24, metrics=Registry(),
                            journal=journal2)
    assert eng2.recover() == 1
    with eng2._lock:
        (rec_req,) = list(eng2._queue)
    assert rec_req.trace.trace_id == root.trace_id
    assert rec_req.trace.parent_id == root.span_id
    assert rec_req.trace.link == "recovers"
    links = [s for s in eng2._spans.snapshot() if s.cat == "link"]
    assert len(links) == 1 and links[0].name == "recovers"
    assert links[0].meta["trace_id"] == root.trace_id
    # the re-admission's OWN admit record carries the continued header
    (new_entry,) = journal2.incomplete()
    assert new_entry.trace == rec_req.trace.to_header()
    _drain(eng2)
    # the retired request span carries the same trace id
    reqs = [s for s in eng2._spans.snapshot() if s.name == "request"]
    assert reqs and reqs[-1].meta["trace_id"] == root.trace_id
    journal2.close()


def test_legacy_journal_without_trace_recovers(params, tmp_path):
    """Pre-trace journals (no 'trace' key) recover unchanged: a fresh
    root is minted, no link span claims a continuity that never was."""
    jpath = str(tmp_path / "legacy.journal")
    with open(jpath, "w", encoding="utf-8") as fh:
        fh.write('{"t":"journal","v":1}\n'
                 '{"t":"admit","id":0,"tokens":[1,9,17],"steps":8,'
                 '"temperature":0.0,"topp":0.9,"seed":7,"slo":null,'
                 '"cursor":0}\n')
    journal = RequestJournal(jpath)
    eng = make_engine(params, journal=journal)
    assert eng.recover() == 1
    with eng._lock:
        (req,) = list(eng._queue)
    assert req.trace is not None and req.trace.link is None
    assert [s for s in eng._spans.snapshot() if s.cat == "link"] == []
    _drain(eng)
    journal.close()


# --------------------------------------------- seam 2: two-server handoff


@pytest.mark.slow
def test_two_server_handoff_one_trace_tracejoin_clean(params):
    """THE tracejoin acceptance gate: a real two-server disagg run —
    prefill pool + decode pool over HTTP + the TCP page channel — keeps
    one trace_id end to end; the two /debug/timeline NDJSON exports
    stitch into ONE valid Chrome trace with zero orphans and the
    handoff send/recv pair bridging the pools."""
    import tracejoin

    from distributed_llama_tpu.obs.spans import validate_chrome_trace
    from distributed_llama_tpu.runtime.server import InferenceServer

    tok = _IdTokenizer()
    prefill_srv = InferenceServer(
        SPEC, params, tok, "127.0.0.1", 0, slots=2, steps=16,
        temperature=0.0, topp=0.9, seed=5, quiet=True, prefill_chunk=4,
        page_size=4, kv_pages=24, disagg_role="prefill")
    prefill_srv.start()
    decode_srv = InferenceServer(
        SPEC, params, tok, "127.0.0.1", 0, slots=2, steps=16,
        temperature=0.0, topp=0.9, seed=5, quiet=True, prefill_chunk=4,
        page_size=4, kv_pages=24, disagg_role="decode",
        disagg_peer=f"127.0.0.1:{prefill_srv.port}")
    decode_srv.start()
    try:
        body = json.dumps({"prompt": "abcdefgh", "steps": 14}).encode()
        rq = urllib.request.Request(
            f"http://127.0.0.1:{decode_srv.port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(rq, timeout=120) as r:
            out = json.loads(r.read())
        assert out["steps"] > 0

        def export(srv):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/timeline"
                    f"?format=ndjson", timeout=30) as r:
                return [json.loads(ln) for ln in
                        r.read().decode().strip().splitlines()
                        if json.loads(ln).get("span") != "_meta"]

        spans_d = export(decode_srv)
        spans_p = export(prefill_srv)
        doc, report = tracejoin.join_pools(spans_d, spans_p, "decode",
                                           "prefill")
        assert report["orphans"] == [], report["orphans"]
        assert report["pairs"] >= 1
        assert report["traces_joined"], "no trace spans both pools"
        validate_chrome_trace(doc)
        tid = report["traces_joined"][0]
        # the handoff pair bridges the pools under ONE trace id
        sends = [s for s in spans_d if s.get("span") == "handoff"
                 and s.get("cat") == "handoff"]
        recvs = [s for s in spans_p if s.get("span") == "prefill_handoff"]
        assert sends and recvs
        assert sends[0]["trace_id"] == recvs[0]["trace_id"] == tid
        # the decode pool's continuation carries the handoff link
        links = [s for s in spans_d if s.get("cat") == "link"]
        assert links and links[0]["link"] == "handoff"
        assert links[0]["trace_id"] == tid
        # ?trace= filters the timeline to that one trace on both pools
        with urllib.request.urlopen(
                f"http://127.0.0.1:{decode_srv.port}/debug/timeline"
                f"?trace={tid}", timeout=30) as r:
            filtered = json.loads(r.read())
        assert filtered["traceEvents"]
        assert all(ev["args"].get("trace_id") == tid
                   for ev in filtered["traceEvents"])
    finally:
        decode_srv.stop()
        prefill_srv.stop()


# --------------------------------------- seam 3: kill mid-handoff (pair)


def test_handoff_then_crash_recovery_keeps_one_trace(params, tmp_path):
    """Seams chained: prefill->decode handoff (one trace, handoff link),
    then a decode-pool crash + recovery (same trace again, recovers
    link) — the request's whole three-process life joins on one id."""
    from distributed_llama_tpu.runtime.disagg import DisaggPair

    prefill = make_engine(
        params, journal=RequestJournal(str(tmp_path / "p.journal")))
    jd_path = str(tmp_path / "d.journal")
    decode_a = make_engine(params, journal=RequestJournal(jd_path),
                           remote=True)
    pair = DisaggPair(prefill, decode_a, channel_host="127.0.0.1")
    from distributed_llama_tpu.runtime.disagg import prefill_stub

    tokens = [1, 9, 17, 25, 31, 7, 3, 44, 11]
    stub, _ = prefill_stub(tokens, 20)
    prefill.submit(stub)
    root_tid = stub.trace.trace_id
    while prefill.step_many(1, quiet=True):
        pass
    h = pair.handoff(stub, 20)
    assert h is not None
    assert h.req.trace.trace_id == root_tid
    assert h.req.trace.link == "handoff"
    # crash the decode pool mid-handoff: journal survives, engine dies
    decode_a._journal.sync(force=True)
    decode_a._journal._fh.close()
    decode_a.close()

    journal_b = RequestJournal(jd_path)
    (entry,) = journal_b.incomplete()
    assert tracectx.parse_header(entry.trace).trace_id == root_tid
    decode_b = make_engine(params, journal=journal_b, remote=True)
    assert decode_b.recover() == 1
    with decode_b._lock:
        (rec_req,) = list(decode_b._queue)
    assert rec_req.trace.trace_id == root_tid
    assert rec_req.trace.link == "recovers"
    _drain(decode_b)
    # the whole life is queryable by the ONE id on the final pool
    spans = decode_b._spans.snapshot(trace_id=root_tid)
    assert {s.name for s in spans} >= {"recovers", "request"}
    pair._server.close()
    prefill.close()
    decode_b.close()
    journal_b.close()


def test_handoff_wire_record_carries_trace(params, tmp_path):
    """entry_to_wire/entry_from_wire round-trip the traceparent, and the
    page channel serves it next to the pages (the TRACE command)."""
    from distributed_llama_tpu.runtime.journal import (entry_from_wire,
                                                       entry_to_wire)
    from distributed_llama_tpu.runtime.page_channel import (
        PageChannelClient, PageChannelServer)

    ctx = tracectx.mint()
    rec = entry_to_wire(
        __import__("distributed_llama_tpu.runtime.journal",
                   fromlist=["JournalEntry"]).JournalEntry(
            rid=3, tokens=[1, 5], steps=8, temperature=0.0, topp=0.9,
            seed=7, trace=ctx.to_header()))
    back = entry_from_wire(rec)
    assert back.trace == ctx.to_header()
    with pytest.raises(ValueError, match="trace"):
        entry_from_wire({**rec, "trace": 7})
    server = PageChannelServer()
    try:
        client = PageChannelClient(f"127.0.0.1:{server.port}")
        server.publish("h1", [], trace=ctx.to_header())
        assert client.trace("h1") == ctx.to_header()
        assert client.trace("nope") is None
        server.publish("h2", [])  # trace-less publish still serves
        assert client.trace("h2") is None
        server.retire("h1")
        assert client.trace("h1") is None
    finally:
        server.close()
