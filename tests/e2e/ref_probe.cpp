// Cross-binary parity probe: drives the REFERENCE implementation's
// public API (compiled unmodified from /root/reference/src, headers via
// -I) and dumps the per-step token ids + raw logits so the test suite can
// compare them numerically against this repo's Engine on the same fixture.
//
// This file is part of *this* repo's test harness — it contains no code
// from the reference; it only calls the entry points the reference's own
// main.cpp uses (main.cpp:38-63, tokenizer.cpp:321-394).
//
// Usage: ref_probe <model.bin> <tokenizer.bin> <prompt> <steps> <logits.out>
//
// Output (stdout): one "TOK <pos> <token> <next>" line per step, where
// <next> is the forced prompt token while the prompt lasts, else the
// argmax of the logits (the temperature=0 sampling path). Logits for every
// step are appended raw-f32 to <logits.out> (steps x vocabSize).

#include <cstdio>
#include <cstdlib>
#include <cstring>

#include "quants.hpp"
#include "socket.hpp"
#include "tokenizer.hpp"
#include "transformer-tasks.hpp"
#include "transformer.hpp"

int main(int argc, char** argv) {
    if (argc != 6) {
        fprintf(stderr,
                "usage: ref_probe MODEL TOKENIZER PROMPT STEPS LOGITS_OUT\n");
        return 2;
    }
    char* modelPath = argv[1];
    char* tokenizerPath = argv[2];
    char* prompt = argv[3];
    int steps = atoi(argv[4]);
    FILE* logitsOut = fopen(argv[5], "wb");
    if (logitsOut == NULL) {
        fprintf(stderr, "cannot open %s\n", argv[5]);
        return 2;
    }

    initQuants();
    SocketPool* socketPool = SocketPool::connect(0, NULL, NULL);
    TransformerSpec spec =
        Transformer::loadSpecFromFile(modelPath, 1, F32, F32);
    Transformer transformer =
        Transformer::loadRootFromFile(modelPath, &spec, socketPool);
    Inference inference = Inference(1, &transformer, socketPool);

    Tokenizer tokenizer(tokenizerPath, spec.vocabSize);
    int* promptTokens = (int*)malloc((strlen(prompt) + 3) * sizeof(int));
    int numPromptTokens = 0;
    tokenizer.encode(prompt, 1, 0, promptTokens, &numPromptTokens);
    if (numPromptTokens < 1) {
        fprintf(stderr, "empty prompt encoding\n");
        return 1;
    }

    int token = promptTokens[0];
    for (int pos = 0; pos < steps; pos++) {
        float* logits = inference.infer(token, pos);
        fwrite(logits, sizeof(float), spec.vocabSize, logitsOut);
        int next;
        if (pos < numPromptTokens - 1) {
            next = promptTokens[pos + 1];
        } else {
            next = 0;
            for (int i = 1; i < spec.vocabSize; i++) {
                if (logits[i] > logits[next]) next = i;
            }
        }
        printf("TOK %d %d %d\n", pos, token, next);
        token = next;
    }
    fclose(logitsOut);
    free(promptTokens);
    delete socketPool;
    return 0;
}
