"""On-device fused generation loop vs the per-step host loop.

The fused loop (runtime/decode.py) must reproduce the host loop's observable
behavior exactly: same sampler semantics (argmax / multinomial / top-p with
the reference's xorshift coin stream), same forced-prompt schedule, same stop
on BOS.
"""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.ops.quants import FloatType

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=256, seq_len=32,
                       weights_float_type=FloatType.F32)


@pytest.mark.parametrize("temperature,topp", [(0.8, 0.9), (1.0, 0.0),
                                              (0.5, 1.5)])
def test_sample_device_matches_host(temperature, topp):
    import jax.numpy as jnp

    from distributed_llama_tpu.runtime.decode import sample_device
    from distributed_llama_tpu.runtime.sampling import Sampler, softmax_f32

    rng = np.random.default_rng(17)
    host = Sampler(128, temperature, topp, seed=42)
    for i in range(20):
        logits = (rng.standard_normal(128) * 3).astype(np.float32)
        coin = host.rng.f32()
        # replay the same coin through the host sampler's strategies
        probs = softmax_f32(logits / np.float32(temperature))
        from distributed_llama_tpu.runtime.sampling import (sample_mult,
                                                            sample_topp)

        if topp <= 0 or topp >= 1:
            want = sample_mult(probs, coin)
        else:
            want = sample_topp(probs, topp, coin)
        got = int(sample_device(jnp.asarray(logits), jnp.float32(coin),
                                temperature, topp))
        assert got == want, f"iter {i}: {got} != {want}"


def test_sample_device_argmax():
    import jax.numpy as jnp

    from distributed_llama_tpu.runtime.decode import sample_device

    logits = np.asarray([0.1, 2.0, -1.0, 1.9], np.float32)
    assert int(sample_device(jnp.asarray(logits), jnp.float32(0.3),
                             0.0, 0.9)) == 1


def test_sample_device_degenerate_nucleus_matches_host():
    """topp < 1/v keeps nothing: both device samplers and the host fall
    back to the argmax (shared _nucleus_walk)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.runtime.decode import (sample_device,
                                                      sample_device_dynamic)
    from distributed_llama_tpu.runtime.sampling import sample_topp, softmax_f32

    logits = np.zeros(64, np.float32)
    logits[17] = 1e-4
    want = sample_topp(softmax_f32(logits), 1e-6, 0.7)
    assert want == 17
    assert int(sample_device(jnp.asarray(logits), jnp.float32(0.7),
                             1.0, 1e-6)) == want
    assert int(sample_device_dynamic(jnp.asarray(logits), jnp.float32(0.7),
                                     jnp.float32(1.0),
                                     jnp.float32(1e-6))) == want


@pytest.mark.parametrize("temperature,topp", [(0.8, 0.9), (1.0, 0.0),
                                              (0.5, 1.5), (0.0, 0.9)])
def test_sample_device_dynamic_matches_static(temperature, topp):
    """The traced-params sampler must agree with the static one on every
    strategy (the strategies differ only in how the branch is selected)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.runtime.decode import (sample_device,
                                                      sample_device_dynamic)

    rng = np.random.default_rng(23)
    for _ in range(10):
        logits = (rng.standard_normal(128) * 3).astype(np.float32)
        coin = float(rng.uniform())
        a = int(sample_device(jnp.asarray(logits), jnp.float32(coin),
                              temperature, topp))
        b = int(sample_device_dynamic(jnp.asarray(logits),
                                      jnp.float32(coin),
                                      jnp.float32(temperature),
                                      jnp.float32(topp)))
        assert a == b


@pytest.mark.parametrize("temperature", [0.0, 0.9])
def test_fused_loop_matches_per_step_generate(temperature):
    """generate_fast must emit the same token chain as generate()."""
    from distributed_llama_tpu.io.tokenizer import write_tokenizer, Tokenizer
    from distributed_llama_tpu.runtime.generate import (Engine, generate,
                                                        generate_fast)
    from distributed_llama_tpu.runtime.sampling import Sampler

    params = synth_params(SPEC, q40=False, seed=3, scale=0.3)

    import tempfile

    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    pieces = pieces[:SPEC.vocab_size - 2] + [b" ", b"hi"]
    scores = [0.0] * len(pieces)
    with tempfile.NamedTemporaryFile(suffix=".bin") as f:
        write_tokenizer(f.name, pieces, scores)
        tok = Tokenizer(f.name, SPEC.vocab_size)

    eng1 = Engine(SPEC, params)
    out1, _ = generate(eng1, tok, Sampler(SPEC.vocab_size, temperature, 0.9,
                                          seed=7),
                       "hi", steps=12, quiet=True)
    eng2 = Engine(SPEC, params)
    out2, _ = generate_fast(eng2, tok, Sampler(SPEC.vocab_size, temperature,
                                               0.9, seed=7),
                            "hi", steps=12, quiet=True)
    assert out1 == out2


def test_fused_loop_rng_stream_rewind_on_early_bos():
    """A BOS-terminated sampled chain must leave the sampler's xorshift
    stream exactly where the per-step loop would have — reusing the Sampler
    afterwards has to stay equivalent between the two paths."""
    from distributed_llama_tpu.io.tokenizer import write_tokenizer, Tokenizer
    from distributed_llama_tpu.runtime.generate import (Engine, generate,
                                                        generate_fast)
    from distributed_llama_tpu.runtime.sampling import Sampler

    # all-zero model -> uniform sampling probs; BOS fires when a coin lands
    # in its 1/vocab bucket
    params = synth_params(SPEC, q40=False, seed=3, scale=0.0)
    params["wcls"] = np.zeros_like(params["wcls"])
    params["tok_embedding"] = np.zeros_like(params["tok_embedding"])

    import tempfile

    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    pieces = pieces[:SPEC.vocab_size - 2] + [b" ", b"hi"]
    with tempfile.NamedTemporaryFile(suffix=".bin") as f:
        write_tokenizer(f.name, pieces, [0.0] * len(pieces))
        tok = Tokenizer(f.name, SPEC.vocab_size)

    steps = 12
    n_prompt = len(tok.encode("hi", bos=True, eos=False))
    n_sampled = steps - (n_prompt - 1)
    # find a seed whose uniform-multinomial chain hits BOS mid-stream:
    # multinomial index = searchsorted(uniform cdf, coin) = floor(coin*vocab)
    from distributed_llama_tpu.utils.rng import Xorshift64

    seed = next(
        s for s in range(1, 2000)
        if any(int(c * SPEC.vocab_size) == 1
               for c in Xorshift64(s).f32_array(n_sampled - 1)))

    s1 = Sampler(SPEC.vocab_size, 0.7, 0.0, seed)  # topp=0 -> multinomial
    out1, _ = generate(Engine(SPEC, params), tok, s1, "hi", steps=steps,
                       quiet=True)
    s2 = Sampler(SPEC.vocab_size, 0.7, 0.0, seed)
    out2, _ = generate_fast(Engine(SPEC, params), tok, s2, "hi", steps=steps,
                            quiet=True)
    assert out1 == out2
    assert len(out1) < steps  # the chain really did terminate early on BOS
    assert s1.rng.state == s2.rng.state  # streams in lockstep for reuse


def test_fused_loop_tensor_parallel():
    """The fused loop must also run with the shard_map step (tp mesh)."""
    from distributed_llama_tpu.io.tokenizer import write_tokenizer, Tokenizer
    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.runtime.generate import (Engine, generate,
                                                        generate_fast)
    from distributed_llama_tpu.runtime.sampling import Sampler

    params = synth_params(SPEC, q40=False, seed=3, scale=0.3)

    import tempfile

    pieces = [b"<unk>", b"<s>", b"</s>"]
    pieces += [f"<0x{i:02X}>".encode() for i in range(256)]
    pieces = pieces[:SPEC.vocab_size - 2] + [b" ", b"hi"]
    with tempfile.NamedTemporaryFile(suffix=".bin") as f:
        write_tokenizer(f.name, pieces, [0.0] * len(pieces))
        tok = Tokenizer(f.name, SPEC.vocab_size)

    ref_eng = Engine(SPEC, params)
    want, _ = generate(ref_eng, tok, Sampler(SPEC.vocab_size, 0.0, 0.9, 1),
                       "hi", steps=10, quiet=True)
    mesh = make_mesh(tp=2)
    eng = Engine(SPEC, params, mesh=mesh)
    got, _ = generate_fast(eng, tok, Sampler(SPEC.vocab_size, 0.0, 0.9, 1),
                           "hi", steps=10, quiet=True)
    assert got == want


def test_steps_change_reuses_one_compiled_loop():
    """Two different --steps budgets must share ONE compiled chain (the
    budget is a traced while_loop bound, not a shape — VERDICT r1 #6: the
    old per-steps key recompiled the full chain per distinct --steps)."""
    from distributed_llama_tpu.runtime.generate import Engine, generate_fast
    from distributed_llama_tpu.runtime.sampling import Sampler

    params = synth_params(SPEC, q40=False, seed=3, scale=0.3)

    class _Tok:
        def encode(self, text, bos=True, eos=False):
            return [1, 5, 9]

        def decode_piece(self, prev, tokn):
            return b"?"

    tok = _Tok()
    eng = Engine(SPEC, params)
    out5, _ = generate_fast(eng, tok, Sampler(SPEC.vocab_size, 0.0, 0.9, 1),
                            "hi", steps=5, quiet=True)
    out9, _ = generate_fast(eng, tok, Sampler(SPEC.vocab_size, 0.0, 0.9, 1),
                            "hi", steps=9, quiet=True)
    assert len(eng._loops) == 1  # same sampling config -> same program
    # the shorter budget is a prefix of the longer greedy chain
    assert out9[:len(out5)] == out5 and len(out9) > len(out5)


def test_aot_decode_loop_matches_jit_path():
    """decode.make_decode_loop_aot (the bench's AOT place-then-compile
    path, layouts pinned to the placed arrays) must produce the same token
    chain as the plain jitted loop."""
    import functools

    import numpy as np

    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)
    from distributed_llama_tpu.runtime.decode import (make_decode_loop,
                                                      make_decode_loop_aot)

    params = synth_params(SPEC, q40=False, seed=3, scale=0.3)
    step = functools.partial(forward, SPEC)
    steps = 6
    padded = np.full((SPEC.seq_len + 1,), -1, dtype=np.int32)
    padded[:3] = [1, 5, 9]
    coins = jnp.zeros((SPEC.seq_len,), jnp.float32)

    run = make_decode_loop(step, SPEC.seq_len, temperature=0.0, topp=0.9)
    want, _ = run(params_to_device(params), init_cache(SPEC),
                  jnp.asarray(padded), jnp.int32(1), coins, jnp.int32(0),
                  jnp.int32(steps))

    compile_and_place = make_decode_loop_aot(step, SPEC.seq_len,
                                             temperature=0.0, topp=0.9)
    compiled, placed = compile_and_place(
        params, jax.eval_shape(lambda: init_cache(SPEC)),
        jnp.asarray(padded), jnp.int32(1), coins, jnp.int32(0),
        jnp.int32(steps))
    got, _ = compiled(placed, init_cache(SPEC), jnp.asarray(padded),
                      jnp.int32(1), coins, jnp.int32(0), jnp.int32(steps))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
