"""Paged flash-decode Pallas kernel (ISSUE 11), interpret-mode gates.

Contract layers:

* kernel vs the XLA gather path: element-level agreement at the flash
  tolerance (the split-KV accumulation reassociates softmax sums across
  page boundaries — reassociation-only deltas, same contract as the
  prefill flash kernel) on BOTH hot shapes: single-token decode and the
  (B, K) speculative-verify window, scrambled physical pages included;
* BITWISE invariants: physical page placement is invisible (any pool
  permutation + table update reproduces identical bytes), and dead
  writes parked on the scrap page / junk beyond the causal bound never
  reach the output;
* Q8 pages: the in-kernel dequant agrees with the XLA fallback's
  gather-side dequant (identical value map, flash-tolerance reduction);
* routing: the ONE maybe_paged_flash_decode gate drives the kernel
  through models/llama.paged_decode_attention + spec_verify_attention
  and both tp factories — pinned over tp x scheme x kv-quant with the
  XLA route as reference.
"""

import numpy as np
import pytest


def _pool(L=2, P=13, ps=8, n_kv=2, hs=128, seed=0):
    rng = np.random.default_rng(seed)
    k4 = rng.normal(size=(L * P, ps, n_kv, hs)).astype(np.float32)
    v4 = rng.normal(size=(L * P, ps, n_kv, hs)).astype(np.float32)
    return k4, v4


def _scrambled_table(B, max_pages, P, seed=1):
    """Physical ids deliberately non-contiguous and interleaved across
    rows (never the scrap page 0)."""
    rng = np.random.default_rng(seed)
    ids = 1 + rng.permutation(P - 1)[:B * max_pages]
    return ids.reshape(B, max_pages).astype(np.int32)


def _xla_reference(q, k4, v4, layer, pos, table, ps, P, kv_mul, t_len):
    """The XLA gather path's math, verbatim (paged_decode_attention /
    spec_verify_attention read side)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import attention_core

    B, max_pages = table.shape
    n_kv, hs = k4.shape[2], k4.shape[3]
    s_virt = max_pages * ps
    rows = (layer * P + table).reshape(-1)
    k_c = jnp.take(jnp.asarray(k4), jnp.asarray(rows), axis=0).reshape(
        B, s_virt, n_kv, hs)
    v_c = jnp.take(jnp.asarray(v4), jnp.asarray(rows), axis=0).reshape(
        B, s_virt, n_kv, hs)
    q_pos = jnp.asarray(pos)[:, None] + jnp.arange(t_len)[None, :]
    mask = jnp.arange(s_virt)[None, None, :] <= q_pos[:, :, None]
    return np.asarray(attention_core(
        hs, kv_mul, jnp.asarray(q).reshape(B, t_len, n_kv * kv_mul, hs),
        k_c, v_c, mask)).reshape(B, t_len, -1)


@pytest.mark.parametrize("kv_mul,pos", [(1, [0, 5, 31]), (2, [7, 30, 16]),
                                        (4, [3, 3, 12])])
def test_paged_decode_matches_xla_gather(kv_mul, pos):
    """Decode (t=1) over scrambled physical pages: the page-table DMA
    walk reproduces the XLA gather path at the flash tolerance,
    last-partial-page offsets included (pos mid-page)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_paged_attention import \
        paged_decode_attention_kernel

    L, P, ps, n_kv, hs = 2, 13, 8, 2, 128
    B, max_pages = 3, 4
    k4, v4 = _pool(L, P, ps, n_kv, hs, seed=kv_mul)
    table = _scrambled_table(B, max_pages, P)
    rng = np.random.default_rng(11 + kv_mul)
    q = rng.normal(size=(B, 1, n_kv * kv_mul * hs)).astype(np.float32)
    pos = np.asarray(pos, np.int32)

    got = paged_decode_attention_kernel(
        jnp.asarray(q), jnp.asarray(k4), jnp.asarray(v4), 1, pos,
        jnp.asarray(table), page_size=ps, n_pages=P, kv_mul=kv_mul,
        t_len=1, interpret=True)
    want = _xla_reference(q, k4, v4, 1, pos, table, ps, P, kv_mul, 1)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("kv_mul,t_len", [(1, 3), (2, 4), (1, 8)])
def test_paged_verify_matches_xla_gather_incl_budget_edge(kv_mul, t_len):
    """The (B, K) speculative-verify window: stacked causal masks per
    query, with one row pinned at the BUDGET EDGE — its window extends
    past the virtual plane (the dead writes went to the scrap page;
    reads must still agree with the XLA mask semantics)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_paged_attention import \
        paged_decode_attention_kernel

    L, P, ps, n_kv, hs = 2, 13, 8, 2, 128
    B, max_pages = 3, 4
    s_virt = max_pages * ps
    k4, v4 = _pool(L, P, ps, n_kv, hs, seed=t_len)
    table = _scrambled_table(B, max_pages, P)
    rng = np.random.default_rng(7 + t_len)
    q = rng.normal(size=(B, t_len, n_kv * kv_mul * hs)).astype(np.float32)
    # row 2 at the budget edge: pos + t_len - 1 >= s_virt
    pos = np.asarray([0, 9, s_virt - 2], np.int32)

    got = paged_decode_attention_kernel(
        jnp.asarray(q), jnp.asarray(k4), jnp.asarray(v4), 0, pos,
        jnp.asarray(table), page_size=ps, n_pages=P, kv_mul=kv_mul,
        t_len=t_len, interpret=True)
    want = _xla_reference(q, k4, v4, 0, pos, table, ps, P, kv_mul, t_len)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5,
                               atol=1e-5)


def test_paged_kernel_bitwise_invariant_to_page_placement():
    """THE paged invariant: permuting the pool's physical pages (and
    remapping the table) reproduces bit-identical output — the kernel
    reads pages in logical order through the table, never by address."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_paged_attention import \
        paged_decode_attention_kernel

    L, P, ps, n_kv, hs = 2, 11, 8, 2, 128
    B, max_pages = 2, 4
    k4, v4 = _pool(L, P, ps, n_kv, hs, seed=5)
    table = _scrambled_table(B, max_pages, P, seed=5)
    rng = np.random.default_rng(5)
    q = rng.normal(size=(B, 1, n_kv * hs)).astype(np.float32)
    pos = np.asarray([13, 30], np.int32)

    base = paged_decode_attention_kernel(
        jnp.asarray(q), jnp.asarray(k4), jnp.asarray(v4), 1, pos,
        jnp.asarray(table), page_size=ps, n_pages=P, kv_mul=1, t_len=1,
        interpret=True)
    # permute physical pages 1..P-1 (scrap page 0 stays put), remap table
    perm = np.concatenate([[0], 1 + rng.permutation(P - 1)])
    k5 = k4.reshape(L, P, ps, n_kv, hs)
    v5 = v4.reshape(L, P, ps, n_kv, hs)
    k5p, v5p = np.empty_like(k5), np.empty_like(v5)
    k5p[:, perm], v5p[:, perm] = k5, v5
    moved = paged_decode_attention_kernel(
        jnp.asarray(q), jnp.asarray(k5p.reshape(L * P, ps, n_kv, hs)),
        jnp.asarray(v5p.reshape(L * P, ps, n_kv, hs)), 1, pos,
        jnp.asarray(perm[table]), page_size=ps, n_pages=P, kv_mul=1,
        t_len=1, interpret=True)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(moved))


def test_paged_kernel_ignores_scrap_and_dead_pages():
    """Scrap-page content (dead writes from parked rows / budget-edge
    verify overflows), junk beyond a row's clock inside its LAST live
    page, and unmapped pool pages must all be invisible — poison them
    and require bit-identical output."""
    import jax.numpy as jnp

    from distributed_llama_tpu.ops.pallas_paged_attention import \
        paged_decode_attention_kernel

    L, P, ps, n_kv, hs = 1, 9, 8, 2, 128
    B, max_pages = 2, 3
    k4, v4 = _pool(L, P, ps, n_kv, hs, seed=3)
    table = np.asarray([[2, 5, 7], [4, 1, 3]], np.int32)
    rng = np.random.default_rng(3)
    q = rng.normal(size=(B, 1, n_kv * hs)).astype(np.float32)
    pos = np.asarray([11, 4], np.int32)  # mid-page clocks

    def run(k, v):
        return np.asarray(paged_decode_attention_kernel(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), 0, pos,
            jnp.asarray(table), page_size=ps, n_pages=P, kv_mul=1,
            t_len=1, interpret=True))

    clean = run(k4, v4)
    k4p, v4p = k4.copy(), v4.copy()
    k4p[0], v4p[0] = 1e9, -1e9              # the scrap page
    k4p[6], v4p[6] = 1e9, -1e9              # a page no table maps
    k4p[5, 4:], v4p[5, 4:] = 1e9, -1e9      # row 0's last live page
    #                                         (pos 11 = offset 3): junk
    #                                         beyond the clock
    k4p[1, 5:], v4p[1, 5:] = 1e9, -1e9      # row 1's last live page
    poisoned = run(k4p, v4p)
    np.testing.assert_array_equal(clean, poisoned)


def test_paged_kernel_q8_matches_xla_dequant_fallback():
    """Q8 pages: the in-kernel page-loop dequant must agree with the XLA
    fallback's gather-side dequant (identical codes*delta value map; the
    only deltas are the flash reduction reassociation)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import attention_core
    from distributed_llama_tpu.ops.pallas_paged_attention import \
        paged_decode_attention_kernel_q8
    from distributed_llama_tpu.ops.quants import QK, quantize_q80_jax

    L, P, ps, n_kv, hs, kv_mul = 2, 13, 8, 2, 128, 2
    B, max_pages = 3, 4
    nb = n_kv * hs // QK
    s_virt = max_pages * ps
    k4, v4 = _pool(L, P, ps, n_kv, hs, seed=9)
    kq, kd = quantize_q80_jax(k4.reshape(L * P, ps, n_kv * hs))
    vq, vd = quantize_q80_jax(v4.reshape(L * P, ps, n_kv * hs))
    kq4 = kq.reshape(L * P, ps, n_kv, hs)
    vq4 = vq.reshape(L * P, ps, n_kv, hs)
    table = _scrambled_table(B, max_pages, P, seed=9)
    rng = np.random.default_rng(9)
    q = rng.normal(size=(B, 1, n_kv * kv_mul * hs)).astype(np.float32)
    pos = np.asarray([0, 17, 31], np.int32)

    got = paged_decode_attention_kernel_q8(
        jnp.asarray(q), kq4, kd, vq4, vd, 1, pos, jnp.asarray(table),
        page_size=ps, n_pages=P, kv_mul=kv_mul, t_len=1, interpret=True)

    rows = jnp.asarray((1 * P + table).reshape(-1))

    def deq(codes, d):
        c = jnp.take(codes, rows, axis=0).reshape(B, s_virt, n_kv, hs)
        dd = jnp.take(d, rows, axis=0).reshape(B, s_virt, nb)
        y = (c.astype(jnp.float32).reshape(B, s_virt, nb, QK)
             * dd.astype(jnp.float32)[..., None])
        return y.reshape(B, s_virt, n_kv, hs)

    mask = jnp.arange(s_virt)[None, None, :] <= jnp.asarray(pos)[:, None,
                                                                 None]
    want = attention_core(hs, kv_mul,
                          jnp.asarray(q).reshape(B, 1, n_kv * kv_mul, hs),
                          deq(kq4, kd), deq(vq4, vd), mask)
    np.testing.assert_allclose(
        np.asarray(got).reshape(B, -1),
        np.asarray(want).reshape(B, -1), rtol=1e-5, atol=1e-5)


def test_supports_paged_gating():
    """The routing gate: lane-width head_size, bounded verify windows,
    VMEM scratch budget, and the q8 block-divisibility rule."""
    from distributed_llama_tpu.ops.pallas_attention import _VMEM_BUDGET
    from distributed_llama_tpu.ops.pallas_paged_attention import (
        _paged_scratch_bytes, supports_paged)

    assert supports_paged(16, 4, 128, 1)
    assert supports_paged(16, 4, 128, 8)
    assert not supports_paged(16, 4, 128, 9)       # window too deep
    assert not supports_paged(16, 4, 64, 1)        # sub-lane head size
    assert not supports_paged(16, 4, 128, 0)
    # a page plane too big for the double-buffered scratch budget
    huge_ps = _VMEM_BUDGET // (2 * 2 * 4 * 128 * 4) + 128
    assert not supports_paged(huge_ps, 4, 128, 1)
    assert _paged_scratch_bytes(huge_ps, 4, 128, 4, False) > _VMEM_BUDGET
    # q8: flattened (n_kv, hs) row must divide into 32-value blocks
    assert supports_paged(16, 4, 128, 1, itemsize=1, q8=True)
    assert not supports_paged(16, 3, 136, 1, itemsize=1, q8=True)


@pytest.mark.parametrize("kv_quant", ["f32", "q8"])
def test_paged_kernel_routing_single_chip(kv_quant, monkeypatch):
    """Fast-suite routing gate: the single-chip paged step with the
    Pallas route forced on agrees with the XLA gather route, f32 and q8
    — the tp x scheme grid variant below runs the same drive under
    shard_map (slow-marked; ci.sh runs it)."""
    _routing_case(1, "fused", kv_quant, monkeypatch)


@pytest.mark.parametrize("scheme", ["ref", "fused", "overlap"])
@pytest.mark.parametrize("tp", [1, 2, 4])
@pytest.mark.parametrize("kv_quant", ["f32", "q8"])
def test_paged_kernel_routing_tp_scheme_grid(tp, scheme, kv_quant,
                                             monkeypatch):
    """The integration gate over the tp x scheme x kv-quant grid: the
    sharded paged decode step with the Pallas route forced on
    (DLLAMA_ATTN_KERNEL=pallas, interpret mode off-TPU) agrees with the
    XLA gather route — same ONE maybe_paged_flash_decode gate the
    engine uses, exercised through make_sharded_forward_batch_paged
    under every collective scheme."""
    _routing_case(tp, scheme, kv_quant, monkeypatch)


def _routing_case(tp, scheme, kv_quant, monkeypatch):
    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (init_cache_paged,
                                                    init_cache_paged_q8,
                                                    params_to_device)
    from distributed_llama_tpu.models.spec import TransformerSpec
    from distributed_llama_tpu.models.synth import synth_params
    from distributed_llama_tpu.parallel import (
        make_mesh, make_sharded_forward_batch_paged, shard_cache_paged,
        shard_params)

    if len(jax.devices()) < tp:
        pytest.skip(f"needs {tp} devices")
    spec = TransformerSpec(dim=512, hidden_dim=256, n_layers=2, n_heads=4,
                           n_kv_heads=4, vocab_size=64, seq_len=32)
    assert spec.head_size == 128  # the kernel's lane-width gate
    tree = synth_params(spec, q40=False, seed=2, scale=0.2)
    ps, B = 8, 2
    max_pages = spec.seq_len // ps
    P = B * max_pages + 1
    table = _scrambled_table(B, max_pages, P, seed=tp)
    toks = np.asarray([3, 9], np.int32)
    pos = np.asarray([0, 0], np.int32)

    def drive(mode):
        monkeypatch.setenv("DLLAMA_ATTN_KERNEL", mode)
        if tp == 1:
            import functools

            from distributed_llama_tpu.models.llama import \
                forward_batch_paged

            params = params_to_device(tree)
            step = jax.jit(functools.partial(forward_batch_paged, spec,
                                             ps, kv_quant=kv_quant),
                           donate_argnums=1)
            cache = (init_cache_paged_q8(spec, P, ps) if kv_quant == "q8"
                     else init_cache_paged(spec, P, ps))
        else:
            mesh = make_mesh(tp=tp, devices=jax.devices()[:tp])
            params = shard_params(tree, mesh, scheme=scheme)
            step = make_sharded_forward_batch_paged(
                spec, mesh, ps, scheme=scheme, kv_quant=kv_quant)
            cache = shard_cache_paged(
                init_cache_paged_q8(spec, P, ps) if kv_quant == "q8"
                else init_cache_paged(spec, P, ps), mesh)
        out = []
        p = pos.copy()
        for step_i in range(3):
            lg, cache = step(params, cache, jnp.asarray(toks + step_i),
                             jnp.asarray(p), jnp.asarray(table))
            out.append(np.asarray(lg))
            p = p + 1
        return np.stack(out)

    xla = drive("xla")
    pallas = drive("pallas")
    np.testing.assert_allclose(pallas, xla, rtol=2e-5, atol=2e-5)
