"""Deep-GQA (kv_mul=8, the Llama-2-70B head ratio: 64 q heads over 8 kv
heads) through the model-level decode paths — the north-star config's
grouping math at tp-sharded and fully-composed-scheduler scope. (The flash
KERNELS' kv_mul=8 unroll is pinned where the other kv_mul cases live:
tests/test_pallas_attention.py's parametrize lists.)"""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.parallel import make_mesh

# kv_mul = 16/2 = 8, and 2 kv heads still shard over tp=2
SPEC = TransformerSpec(dim=128, hidden_dim=256, n_layers=2, n_heads=16,
                       n_kv_heads=2, vocab_size=96, seq_len=16)

assert SPEC.kv_mul == 8


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=12, scale=0.2)


def test_deep_gqa_tp_parity(params):
    """tp-sharded decode == single chip at kv_mul=8 (grouped heads stay
    whole within each contiguous band)."""
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward, init_cache,
                                                    params_to_device)
    from distributed_llama_tpu.parallel import (make_sharded_forward,
                                                shard_cache, shard_params)

    dev = params_to_device(params)
    c = init_cache(SPEC)
    want = []
    for pos, t in enumerate((7, 11, 3)):
        lg, c = forward(SPEC, dev, c, jnp.asarray([t], jnp.int32),
                        jnp.int32(pos))
        want.append(np.asarray(lg))

    mesh = make_mesh(tp=2)
    fwd = make_sharded_forward(SPEC, mesh)
    ps = shard_params(params, mesh)
    cs = shard_cache(init_cache(SPEC), mesh)
    for pos, t in enumerate((7, 11, 3)):
        lg, cs = fwd(ps, cs, jnp.asarray([t], jnp.int32), jnp.int32(pos))
        np.testing.assert_allclose(np.asarray(lg), want[pos],
                                   rtol=2e-5, atol=2e-5)


def test_deep_gqa_continuous_composed(params):
    """Continuous batching with everything on (sp/tp mesh, fused chains,
    prefill) at kv_mul=8 == the single-chip scheduler."""
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    reqs = [[1, 5, 9], [1, 22], [1, 7, 33, 2]]
    ref, _ = ContinuousEngine(SPEC, params, slots=2, temperature=0.9,
                              topp=0.9, seed=3).run(reqs, 8)
    got, _ = ContinuousEngine(SPEC, params, slots=2, temperature=0.9,
                              topp=0.9, seed=3, mesh=make_mesh(sp=2, tp=2),
                              block_steps=3, prefill_chunk=2).run(reqs, 8)
    assert got == ref
