"""Paged KV cache + radix prefix sharing (ISSUE 6).

Three layers of gates:

* pure-host units: PagePool refcount/free-list invariants, PrefixTree
  match/insert/LRU-eviction semantics, PagedAllocator policy;
* device parity: paged decode logits are BITWISE equal to the contiguous
  cache's, step by ragged step (the property the whole refactor rests on);
* engine behavior: token streams are invisible to paging across every
  scheduler configuration, prefix sharing fires on shared system prompts,
  pages return to the pool on retire AND on mid-prefill cancellation, and
  the memory-model formulas agree at equal capacity.
"""

import numpy as np
import pytest

from distributed_llama_tpu.models.spec import TransformerSpec
from distributed_llama_tpu.models.synth import synth_params
from distributed_llama_tpu.runtime.paging import (PagePool, PagedAllocator,
                                                  PrefixTree, SCRAP_PAGE)

SPEC = TransformerSpec(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
                       n_kv_heads=2, vocab_size=128, seq_len=16)


@pytest.fixture(scope="module")
def params():
    return synth_params(SPEC, q40=False, seed=4, scale=0.3)


# -- PagePool ---------------------------------------------------------------


def test_pool_alloc_order_refcounts_and_free():
    pool = PagePool(4)
    assert pool.n_free == 4
    a, b = pool.alloc(), pool.alloc()
    assert (a, b) == (1, 2)  # lowest-first, deterministic
    assert pool.refcount(a) == 1
    pool.retain(a)
    pool.release(a)
    assert pool.refcount(a) == 1  # still held once
    assert pool.n_free == 2
    pool.release(a)
    assert pool.refcount(a) == 0 and pool.n_free == 3
    # freed page is reusable, and the scrap page id is never handed out
    got = {pool.alloc() for _ in range(3)}
    assert SCRAP_PAGE not in got and a in got
    assert pool.alloc() is None  # dry pool reports, not raises


def test_pool_release_unallocated_raises():
    pool = PagePool(2)
    with pytest.raises(ValueError):
        pool.release(1)
    with pytest.raises(ValueError):
        pool.retain(2)


def test_pool_free_list_stays_lowest_first_after_release():
    pool = PagePool(4)
    pages = [pool.alloc() for _ in range(4)]
    for pid in pages:           # release in ALLOC order: appends go high
        pool.release(pid)
    assert [pool.alloc() for _ in range(4)] == pages  # lowest-first again


# -- PrefixTree -------------------------------------------------------------


def _tree(n_pages=8, ps=4):
    pool = PagePool(n_pages)
    return pool, PrefixTree(pool, ps)


def test_tree_insert_match_full_pages_only():
    pool, tree = _tree()
    toks = [1, 5, 9, 14, 23, 40]  # 1.5 pages at ps=4
    pages = [pool.alloc(), pool.alloc()]
    assert tree.insert(toks, pages) == 1  # only the FULL first page adopted
    assert len(tree) == 1
    # match retains a ref for the caller
    got = tree.match(toks)
    assert got == [pages[0]]
    assert pool.refcount(pages[0]) == 3  # owner + tree + matcher
    # a diverging suffix still shares the aligned prefix
    assert tree.match([1, 5, 9, 14, 99, 98]) == [pages[0]]
    # a diverging FIRST page shares nothing
    assert tree.match([2, 5, 9, 14]) == []


def test_tree_two_level_match_and_recency_eviction():
    pool, tree = _tree()
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    pages = [pool.alloc(), pool.alloc()]
    tree.insert(toks, pages)
    other = [9, 9, 9, 9]
    p_other = [pool.alloc()]
    tree.insert(other, p_other)
    tree.match(other)  # refresh: 'other' is now most-recent
    pool.release(p_other[0])  # drop the matcher's ref again
    for pid in pages + p_other:
        pool.release(pid)  # owners retire: tree-only refs remain
    # eviction unwinds the LRU chain leaf-first: the [1..8] branch goes
    # before the freshly-touched [9,9,9,9] leaf
    assert tree.evict_lru(2) == 2
    assert tree.match(toks) == []
    assert tree.match(other) == [p_other[0]]
    pool.release(p_other[0])


def test_eviction_order_is_strict_lru_per_touch_ticks():
    """ISSUE 12 satellite: LRU ordering is EXPLICIT — every node touch
    takes its own monotonic tick (no wall clock, no shared walk
    timestamp), so eviction among equal-refcount leaves is a strict
    total order determined by touch history alone, even for leaves
    published in the SAME insert batch."""
    pool, tree = _tree(n_pages=8, ps=2)
    pages = {}
    for toks in ([1, 1], [2, 2], [3, 3]):
        p = [pool.alloc()]
        tree.insert(toks, p)
        pool.release(p[0])
        pages[toks[0]] = p[0]
    # refresh in the order 2, 1: LRU is now 3 < 2 < 1
    for t in (2, 1):
        got = tree.match([t, t])
        pool.release(got[0])
    evicted = []
    for _ in range(3):
        assert tree.evict_lru(1) == 1
        for t, p in pages.items():
            if pool.refcount(p) == 0 and t not in evicted:
                evicted.append(t)
    assert evicted == [3, 2, 1]
    # ... and ticks are strictly per-node: one insert's nodes never tie
    pool2, tree2 = _tree(n_pages=8, ps=2)
    ps2 = [pool2.alloc(), pool2.alloc()]
    tree2.insert([5, 5, 6, 6], ps2)
    ticks = sorted(n.last_used for n in tree2.nodes())
    assert ticks[0] != ticks[1]


def test_tree_interior_nodes_not_evicted_under_live_children():
    pool, tree = _tree()
    toks = [1, 2, 3, 4, 5, 6, 7, 8]
    pages = [pool.alloc(), pool.alloc()]
    tree.insert(toks, pages)
    pool.release(pages[0])  # owner keeps only the SECOND page pinned
    # page 2 still slot-held (refcount 2): only the leaf would be
    # evictable, but it is pinned -> nothing can be freed
    assert tree.evict_lru(2) == 0
    pool.release(pages[1])
    assert tree.evict_lru(2) == 2  # now leaf, then its parent
    assert len(tree) == 0


def test_tree_clear_releases_everything():
    pool, tree = _tree()
    pages = [pool.alloc(), pool.alloc()]
    tree.insert([1, 2, 3, 4, 5, 6, 7, 8], pages)
    for pid in pages:
        pool.release(pid)
    assert pool.n_free == 6
    assert tree.clear() == 2
    assert pool.n_free == 8 and len(tree) == 0


# -- PagedAllocator ---------------------------------------------------------


def test_allocator_evicts_idle_tree_pages_when_dry():
    a = PagedAllocator(2, page_size=4)
    p1 = a.alloc_page()
    a.insert_prefix([1, 2, 3, 4], [p1])
    a.release_pages([p1])      # slot retires; tree still holds p1
    p2 = a.alloc_page()
    assert a.n_free == 0
    p3 = a.alloc_page()        # dry -> evicts the idle tree leaf
    assert p3 == p1 and a.evictions == 1
    assert a.alloc_page() is None  # truly dry: everything slot-held
    for pid in (p2, p3):
        a.release_pages([pid])


def test_allocator_hit_miss_counters_and_pages_for():
    a = PagedAllocator(8, page_size=4)
    assert (a.pages_for(1), a.pages_for(4), a.pages_for(5)) == (1, 1, 2)
    # counting rides record_admission, NOT match_prefix: a dry-pool
    # requeue re-matches every retry and must not inflate the figures
    assert a.match_prefix([1, 2, 3, 4]) == []
    assert (a.prefix_hits, a.prefix_misses) == (0, 0)
    a.record_admission(0)
    p = a.alloc_page()
    a.insert_prefix([1, 2, 3, 4], [p])
    got = a.match_prefix([1, 2, 3, 4, 9])
    assert got == [p]
    a.record_admission(len(got))
    assert (a.prefix_hits, a.prefix_misses) == (1, 1)
    assert a.hit_rate == 0.5 and a.tokens_saved == 4
    a2 = PagedAllocator(8, page_size=4, prefix_share=False)
    assert a2.match_prefix([1, 2, 3, 4]) == []
    assert a2.insert_prefix([1, 2, 3, 4], [a2.alloc_page()]) == 0


def test_allocator_counters_match_metrics_under_dry_requeues(params):
    """The review-found double-count: with an oversubscribed pool forcing
    dry-pool requeues, the allocator's hit/saved figures (bench + CLI
    summary) must still equal the Prometheus counters — one count per
    STICKING admission, however many retries preceded it."""
    from distributed_llama_tpu.obs.metrics import Registry
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    sys_p = [1] + list(range(20, 28))
    reqs = [sys_p + [40 + i] for i in range(8)]
    reg = Registry()
    eng = ContinuousEngine(SPEC, params, slots=3, temperature=0.0, topp=0.9,
                           seed=3, page_size=4, kv_pages=5, prefill_chunk=4,
                           metrics=reg)
    eng.run(reqs, steps=12)
    a = eng.allocator
    assert reg.get("dllama_prefix_hits_total").value == a.prefix_hits
    assert reg.get("dllama_prefill_tokens_saved_total").value \
        == a.tokens_saved
    assert a.prefix_hits + a.prefix_misses <= len(reqs)


# -- device parity: paged == contiguous, bitwise ----------------------------


@pytest.mark.parametrize("wtype", ["f32", "q40", "f16"])
def test_paged_decode_logits_bitwise_equal_contiguous(wtype):
    """The tentpole property: ragged decode through the page-pool cache
    (scattered physical pages, scrap-parked tails) produces BITWISE the
    contiguous cache's logits, step for step — gathered pages reproduce
    the virtual (B, S) plane exactly and the masked softmax never sees
    the junk beyond a row's clock. Pinned across weight codecs: the Q40
    kernel path and the f16 storage path feed the same cache machinery."""
    import functools

    import jax
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (forward_batch_paged,
                                                    forward_batch_ragged,
                                                    init_cache_batch,
                                                    init_cache_paged,
                                                    params_to_device)

    tree = synth_params(SPEC, q40=(wtype == "q40"), seed=4, scale=0.3)
    if wtype == "f16":
        for k in ("wq", "wk", "wv", "wo", "w1", "w2", "w3", "wcls"):
            tree[k] = tree[k].astype(np.float16)
    params_dev = params_to_device(tree)
    ps, B = 4, 3
    max_pages = SPEC.seq_len // ps
    cache_c = init_cache_batch(SPEC, B)
    cache_p = init_cache_paged(SPEC, B * max_pages + 1, ps)
    # DELIBERATELY scrambled physical pages: row b's logical page j lives
    # at physical 1 + (j * B + b), so contiguous-looking reads would fail
    table = np.zeros((B, max_pages), np.int32)
    for b in range(B):
        table[b] = 1 + np.arange(max_pages) * B + b
    step_c = jax.jit(functools.partial(forward_batch_ragged, SPEC),
                     donate_argnums=1)
    step_p = jax.jit(functools.partial(forward_batch_paged, SPEC, ps),
                     donate_argnums=1)
    rng = np.random.default_rng(7)
    pos = np.zeros((B,), np.int32)
    for _ in range(12):
        toks = rng.integers(2, 100, (B,)).astype(np.int32)
        lg_c, cache_c = step_c(params_dev, cache_c, jnp.asarray(toks),
                               jnp.asarray(pos))
        lg_p, cache_p = step_p(params_dev, cache_p, jnp.asarray(toks),
                               jnp.asarray(pos), jnp.asarray(table))
        np.testing.assert_array_equal(np.asarray(lg_c), np.asarray(lg_p))
        pos = np.minimum(pos + rng.integers(0, 2, (B,)),
                         SPEC.seq_len - 1).astype(np.int32)


def test_gather_scatter_pages_round_trip(params):
    import jax.numpy as jnp

    from distributed_llama_tpu.models.llama import (gather_pages,
                                                    init_cache_paged,
                                                    scatter_pages)

    ps = 4
    max_pages = SPEC.seq_len // ps
    cache = init_cache_paged(SPEC, max_pages + 1, ps)
    rng = np.random.default_rng(0)
    cache = cache._replace(
        k=jnp.asarray(rng.standard_normal(cache.k.shape), jnp.float32),
        v=jnp.asarray(rng.standard_normal(cache.v.shape), jnp.float32))
    table = jnp.asarray(1 + np.arange(max_pages, dtype=np.int32)[::-1])
    seq = gather_pages(cache, table, ps)
    assert seq.k.shape == (SPEC.n_layers, SPEC.seq_len, SPEC.n_kv_heads,
                           SPEC.head_size)
    back = scatter_pages(cache, seq, table, ps)
    np.testing.assert_array_equal(np.asarray(back.k), np.asarray(cache.k))
    np.testing.assert_array_equal(np.asarray(back.v), np.asarray(cache.v))


# -- engine behavior --------------------------------------------------------


def _run(params, reqs, steps, **kw):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    eng = ContinuousEngine(SPEC, params, slots=kw.pop("slots", 2),
                           temperature=kw.pop("temperature", 0.0),
                           topp=0.9, seed=3, **kw)
    outs, stats = eng.run(reqs, steps)
    return eng, outs, stats


REQS = [[1, 5, 9], [1, 22], [1, 7, 33, 2], [1, 60], [1, 90, 14]]


@pytest.mark.parametrize("kw", [
    dict(page_size=4),
    dict(page_size=2, block_steps=4),
    dict(page_size=4, prefill_chunk=2),
    dict(page_size=4, block_steps=3, prefill_chunk=2),
    dict(page_size=4, temperature=0.9),
])
def test_paged_streams_match_contiguous(params, kw):
    """Paging must be invisible in every request's token stream — across
    fused chains, admission prefill, and sampled decoding."""
    temp = kw.get("temperature", 0.0)
    _, ref, _ = _run(params, REQS, 8, temperature=temp)
    _, got, _ = _run(params, REQS, 8, **dict(kw))
    assert got == ref


@pytest.mark.parametrize("scheme", ["ref", "fused", "overlap"])
def test_paged_streams_match_over_tp_mesh(params, scheme, monkeypatch):
    """Paged decode under ALL THREE tp collective schemes: attention runs
    before the layer tail, so the scheme's schedule (ref gathers, fused
    combines, overlap's ring + deferred gather carry) never sees the
    page table — streams match the single-chip engine."""
    from distributed_llama_tpu.parallel import make_mesh

    _, ref, _ = _run(params, REQS[:3], 8)
    monkeypatch.setenv("DLLAMA_TP_SCHEME", scheme)
    _, got, _ = _run(params, REQS[:3], 8, mesh=make_mesh(tp=2),
                     page_size=4, prefill_chunk=2, block_steps=3)
    assert got == ref


def test_fail_all_clears_tree_and_frees_pool(params):
    """fail_all tears down the radix tree with the rest of the engine
    state: a post-fault loop restarts from a fully-free pool."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                           seed=3, page_size=4, prefill_chunk=4)
    eng.run([[1] + list(range(20, 28))], 12)  # publishes prompt pages
    assert len(eng.allocator.tree) > 0
    eng.submit(Request(tokens=[1, 5], steps=4))
    eng._admit()
    eng.fail_all("fault")
    assert len(eng.allocator.tree) == 0
    assert eng.allocator.n_free == eng.allocator.n_pages


def test_kv_pages_without_page_size_rejected(params):
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    with pytest.raises(ValueError, match="kv_pages requires page_size"):
        ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                         seed=3, kv_pages=8)


def test_paged_rejects_sp_mesh_and_ragged_page_size(params):
    from distributed_llama_tpu.parallel import make_mesh
    from distributed_llama_tpu.runtime.continuous import ContinuousEngine

    with pytest.raises(ValueError, match="sp=1"):
        ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                         seed=3, mesh=make_mesh(sp=2, tp=2), page_size=4)
    with pytest.raises(ValueError, match="must divide"):
        ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                         seed=3, page_size=5)


def test_shared_system_prompt_hits_prefix_tree(params):
    """The serving win: same-system-prompt requests map shared pages
    (copy-free), skip their prefill, and still stream identically."""
    sys_p = [1] + list(range(20, 28))  # 2 full pages at ps=4
    reqs = [sys_p + [40 + i] for i in range(5)]
    _, ref, _ = _run(params, reqs, 12)
    eng, got, _ = _run(params, reqs, 12, page_size=4, prefill_chunk=4)
    assert got == ref
    a = eng.allocator
    assert a.prefix_hits >= 3  # all but the concurrently-admitted first two
    assert a.tokens_saved >= 3 * 8
    assert a.hit_rate > 0


def test_oversubscribed_pool_more_slots_at_equal_pages(params):
    """4 slots over a 2-sequence page budget: the concurrency lever. All
    requests complete, streams match, and the pool never leaks."""
    sys_p = [1] + list(range(20, 28))
    reqs = [sys_p + [40 + i] for i in range(6)]
    _, ref, _ = _run(params, reqs, 12)
    eng, got, st = _run(params, reqs, 12, slots=4, page_size=4, kv_pages=8,
                        prefill_chunk=4)
    assert got == ref
    assert st.max_active > 2  # actually used the extra slots
    a = eng.allocator
    # retired slots dropped their refs: only tree-held pages stay out
    assert a.n_free + len(a.tree) == a.n_pages


def test_pool_capacity_clamps_budget_like_seq_len(params):
    """A request whose step budget exceeds what the pool can ever hold is
    clamped to the pool's positions at admission — the same contract as
    the existing seq_len clamp — instead of being admitted and then
    killed mid-stream by the deadlock breaker."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                           seed=3, page_size=4, kv_pages=2)  # 8 positions
    big = eng.submit(Request(tokens=[1, 5, 9], steps=14))
    ok = eng.submit(Request(tokens=[1, 5], steps=4))  # one page: no clash
    while eng.step_once():
        pass
    assert big.done.is_set() and big.error is None
    assert len(big.out) <= 8  # ran to the pool edge, no further
    assert ok.done.is_set() and ok.error is None and ok.out
    # solo prefix: the clamped stream equals a solo run at the clamped
    # budget (pausing/clamping stayed stream-invisible)
    solo, _ = ContinuousEngine(SPEC, params, slots=1, temperature=0.0,
                               topp=0.9, seed=3).run([[1, 5, 9]], 8)
    assert big.out == solo[0]
    a = eng.allocator
    assert a.n_free + len(a.tree) == 2


def test_dry_pool_requeues_and_completes_fcfs(params):
    """Admissions the pool cannot serve yet wait at the queue head and
    complete once running requests retire — no deadlock, no failure.
    3 requests x 2 pages each (budget 8 at ps=4) through a 4-page pool:
    the third waits for a retirement, then runs."""
    reqs = [[1, 5, 9], [1, 22, 7], [1, 60, 3]]
    _, ref, _ = _run(params, reqs, 8)
    eng, got, _ = _run(params, reqs, 8, slots=3, page_size=4, kv_pages=4,
                       prefix_share=False)
    assert got == ref
    assert eng.allocator.n_free == 4  # nothing leaked, tree empty
    assert len(eng.allocator.tree) == 0


def test_starved_slot_pauses_until_pages_free(params):
    """Mid-decode growth beyond the pool pauses the starved slot (frozen
    through the step, stream-invisible) until a retirement frees pages;
    only a true all-slots deadlock fails a request — the youngest."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    # staggered budgets: req0 needs 2 pages total, req1 needs 3; pool of
    # 4 forces req1 to pause at its third page until req0 retires
    _, ref, _ = _run(params, [[1, 5, 9]], 12, slots=1, prefix_share=False,
                     page_size=4, kv_pages=4)
    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                           seed=3, page_size=4, kv_pages=4,
                           prefix_share=False)
    short = eng.submit(Request(tokens=[1, 22, 7], steps=6))
    long = eng.submit(Request(tokens=[1, 5, 9], steps=12))
    while eng.step_once():
        pass
    assert short.error is None and long.error is None
    assert long.out == ref[0]  # pausing never showed up in the stream
    assert eng.allocator.n_free == 4

    # true deadlock: both slots starved at once -> youngest fails, the
    # older survivor completes on the freed pages
    eng2 = ContinuousEngine(SPEC, params, slots=2, temperature=0.0,
                            topp=0.9, seed=3, page_size=4, kv_pages=2,
                            prefix_share=False)
    a = eng2.submit(Request(tokens=[1, 5], steps=8))
    b = eng2.submit(Request(tokens=[1, 7], steps=8))
    while eng2.step_once():
        pass
    assert a.error is None and a.out
    assert b.error is not None and "exhausted" in b.error
    assert eng2.allocator.n_free == 2


def test_cancelled_prefill_returns_pages_to_pool(params):
    """ISSUE 6 satellite: a request whose consumer vanishes DURING
    admission prefill must hand its pages back immediately (slot refs
    dropped at the admission check, not at the next chain boundary)."""
    from distributed_llama_tpu.runtime.continuous import (ContinuousEngine,
                                                          Request)

    eng = ContinuousEngine(SPEC, params, slots=2, temperature=0.0, topp=0.9,
                           seed=3, page_size=4, prefill_chunk=4,
                           block_steps=4)
    req = Request(tokens=[1] + list(range(30, 38)), steps=12)
    # the consumer disconnects while prefill echoes stream out — the
    # closest deterministic stand-in for a socket dying mid-prefill
    req.on_token = lambda t: setattr(req, "cancelled", True)
    eng.submit(req)
    live = eng.submit(Request(tokens=[1, 5], steps=6))
    while eng.step_many(4):
        pass
    assert req.done.is_set() and req.cancelled
    assert live.done.is_set() and live.error is None
    a = eng.allocator
    # every page is back (free) or idle-shared (tree, refcount 1) — the
    # cancelled slot pinned nothing past its retirement
    assert a.n_free + len(a.tree) == a.n_pages
    for s in eng._pool:
        assert s.pages == []


def test_paged_engine_survives_reuse_with_warm_tree(params):
    """A second run against the same engine matches the first (prefix
    sharing from the warm tree is stream-invisible)."""
    sys_p = [1] + list(range(20, 28))
    reqs = [sys_p + [40 + i] for i in range(4)]
    eng, first, _ = _run(params, reqs, 12, page_size=4, prefill_chunk=4)
    second, _ = eng.run(reqs, 12)
    assert second == first
    assert eng.allocator.prefix_hits > 0


# -- memory model -----------------------------------------------------------


def test_page_pool_bytes_equal_contiguous_at_default_sizing():
    from distributed_llama_tpu.analysis.memory_model import (
        DEFAULT_PAGE_SIZE, default_kv_pages, kv_cache_device_bytes,
        kv_page_pool_bytes)
    from distributed_llama_tpu.analysis.shardcheck import (
        check_paged_equivalence, model_spec)

    for model in ("7b", "13b", "70b"):
        for tp in (1, 2, 4, 8):
            spec = model_spec(model, "q40")
            contig = kv_cache_device_bytes(spec, tp, batch=4)
            paged = kv_page_pool_bytes(
                spec, tp, default_kv_pages(spec, 4, DEFAULT_PAGE_SIZE),
                DEFAULT_PAGE_SIZE, include_scrap=False)
            assert paged == contig, (model, tp)
            # the scrap page is charged when the engine allocates it
            with_scrap = kv_page_pool_bytes(
                spec, tp, default_kv_pages(spec, 4, DEFAULT_PAGE_SIZE),
                DEFAULT_PAGE_SIZE)
            page_bytes = (2 * spec.n_layers * DEFAULT_PAGE_SIZE
                          * (spec.n_kv_heads // tp) * spec.head_size * 4)
            assert with_scrap - contig == page_bytes
            assert check_paged_equivalence(spec, tp, "cfg", contig // 4) \
                == []


def test_shardcheck_flags_paged_formula_drift():
    from distributed_llama_tpu.analysis.shardcheck import (
        check_paged_equivalence, model_spec)

    spec = model_spec("7b", "q40")
    findings = check_paged_equivalence(spec, 1, "cfg", 12345)  # wrong bytes
    assert findings and findings[0].rule == "KV-PAGED"
    ragged = model_spec("7b", "q40")
    ragged = type(ragged)(**{**ragged.__dict__, "seq_len": 2050})
    findings = check_paged_equivalence(ragged, 1, "cfg", 0)
    assert findings and "not a multiple" in findings[0].detail


def test_device_footprint_paged_kv_term():
    from distributed_llama_tpu.analysis.memory_model import (
        default_kv_pages, device_footprint)
    from distributed_llama_tpu.analysis.shardcheck import model_spec

    spec = model_spec("7b", "q40")
    contig = device_footprint(spec, 4, "fused", batch=2)
    paged = device_footprint(spec, 4, "fused", batch=2, kv_page_size=16)
    page_bytes = (2 * spec.n_layers * 16 * (spec.n_kv_heads // 4)
                  * spec.head_size * 4)
    assert paged.kv_cache_bytes == contig.kv_cache_bytes + page_bytes
    half = device_footprint(spec, 4, "fused", batch=2, kv_page_size=16,
                            kv_pages=default_kv_pages(spec, 1, 16))
    assert half.kv_cache_bytes < contig.kv_cache_bytes
