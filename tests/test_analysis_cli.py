"""Exit-code coverage for the analysis CLI (analysis/__main__.py).

Every head gets its zero AND non-zero path: --lint over the repo (clean)
and over a seeded violation (1), --write-baseline round trip, --contracts
(clean), --shardcheck over the full matrix (clean — the acceptance
invocation), over a tiny matrix (fast path), and over a seeded-violation
matrix declaring 70b-tp1 to fit (1). Usage errors exit 2
(tests/test_dlint_repo.py covers the partial-scan refusal)."""

from __future__ import annotations

import json
import textwrap

from distributed_llama_tpu.analysis.__main__ import main


def test_lint_head_clean_repo_exits_zero(capsys):
    assert main(["--lint"]) == 0
    assert "0 new finding(s)" in capsys.readouterr().out


def test_lint_head_seeded_violation_exits_one(tmp_path, capsys):
    bad = tmp_path / "runtime" / "hot.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent("""
        import numpy as np

        def step(logits):
            return np.asarray(logits)
    """), encoding="utf-8")
    assert main(["--lint", str(bad)]) == 1
    assert "D001" in capsys.readouterr().out


def test_write_baseline_round_trip(tmp_path, capsys):
    target = tmp_path / "baseline.txt"
    assert main(["--write-baseline", "--baseline", str(target)]) == 0
    assert target.exists()
    # the freshly written baseline suppresses exactly the current findings
    assert main(["--lint", "--baseline", str(target)]) == 0
    out = capsys.readouterr().out
    assert "0 new finding(s)" in out


def test_contracts_head_exits_zero(capsys):
    assert main(["--contracts"]) == 0
    out = capsys.readouterr().out
    assert "J001" in out and "FAIL" not in out


def test_shardcheck_full_matrix_exits_zero(capsys):
    # the acceptance-criteria invocation
    assert main(["--shardcheck"]) == 0
    out = capsys.readouterr().out
    assert "84 config(s), 0 violating" in out
    assert "FAIL" not in out


def _write_matrix(tmp_path, entries):
    path = tmp_path / "matrix.json"
    path.write_text(json.dumps(entries), encoding="utf-8")
    return path


def test_shardcheck_matrix_override(tmp_path, capsys):
    path = _write_matrix(tmp_path, [
        {"model": "7b", "tp": 4, "scheme": "fused", "wtype": "q40",
         "expect_fits": True}])
    assert main(["--shardcheck", "--shardcheck-matrix", str(path)]) == 0
    assert "1 config(s), 0 violating" in capsys.readouterr().out


def test_shardcheck_seeded_violation_exits_one(tmp_path, capsys):
    # 70B Q40 unsharded cannot fit a 16 GiB chip: declaring it fit must
    # fail with the named budget rule
    path = _write_matrix(tmp_path, [
        {"model": "70b", "tp": 1, "scheme": "ref", "wtype": "q40",
         "expect_fits": True}])
    assert main(["--shardcheck", "--shardcheck-matrix", str(path)]) == 1
    out = capsys.readouterr().out
    assert "HBM-BUDGET" in out and "1 violating" in out


def test_shardcheck_matrix_alone_implies_the_head(tmp_path, capsys):
    # a forgotten --shardcheck must not silently skip the drift gate the
    # matrix encodes (mirrors --write-baseline implying --lint)
    path = _write_matrix(tmp_path, [
        {"model": "70b", "tp": 1, "scheme": "ref", "wtype": "q40",
         "expect_fits": True}])
    assert main(["--shardcheck-matrix", str(path)]) == 1
    assert "HBM-BUDGET" in capsys.readouterr().out


def test_tools_shardcheck_emits_json_report(tmp_path, capsys):
    import tools.shardcheck as ts

    out_path = tmp_path / "report.json"
    matrix = _write_matrix(tmp_path, [
        {"model": "70b", "tp": 8, "scheme": "fused", "wtype": "q40",
         "expect_fits": True},
        {"model": "70b", "tp": 1, "scheme": "ref", "wtype": "q40",
         "expect_fits": False}])
    rc = ts.main(["--matrix", str(matrix), "--json", str(out_path)])
    assert rc == 0
    rep = json.loads(out_path.read_text(encoding="utf-8"))
    assert rep["n_configs"] == 2 and rep["n_violations"] == 0
    by_cfg = {c["config"]: c for c in rep["configs"]}
    assert by_cfg["70b-tp8-fused-q40"]["report"]["fits"] is True
    assert by_cfg["70b-tp1-ref-q40"]["report"]["fits"] is False


def test_tools_shardcheck_single_config_filter(capsys):
    import tools.shardcheck as ts

    assert ts.main(["--config", "70b-tp8-fused-q40"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["n_configs"] == 1
    assert ts.main(["--config", "no-such-config"]) == 2
