"""Prefill/decode disaggregation gates (ISSUE 14).

The acceptance contract: a handed-off request's stream is BITWISE the
single-pool run's (greedy + seeded-sampled, f32 + q8 pages), the page
wire codec is byte-identical to the disk tier's records, handoff edge
cases (mid-transfer cancel, decode-pool radix publish) leave both pools'
page accounting clean, and the virtual-clock two-pool sweep shows the
disaggregated topology beating the colocated baseline on interactive
SLO attainment at equal simulated hardware.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

from distributed_llama_tpu.models.spec import TransformerSpec  # noqa: E402
from distributed_llama_tpu.models.synth import synth_params  # noqa: E402
from distributed_llama_tpu.obs.metrics import Registry  # noqa: E402
from distributed_llama_tpu.runtime import pagewire  # noqa: E402
from distributed_llama_tpu.runtime.continuous import (  # noqa: E402
    ContinuousEngine, Request)
from distributed_llama_tpu.runtime.disagg import (  # noqa: E402
    DisaggPair, decode_request, entry_for_stub, make_priority_hold,
    prefill_stub, stub_needs_handoff)
from distributed_llama_tpu.runtime.journal import (  # noqa: E402
    RequestJournal, entry_from_wire, entry_to_wire)

SPEC_KW = dict(dim=64, hidden_dim=160, n_layers=2, n_heads=4,
               n_kv_heads=2, vocab_size=128, seq_len=32)

# prompts spanning >= 2 full pages (page_size 4) so handoffs ship real
# pages; the first two share their full-page prefix (the radix-publish
# gate), the third is short (a local completion on the prefill pool)
REQS = [[1, 9, 17, 25, 31, 7, 3, 44, 11],
        [1, 9, 17, 25, 31, 7, 3, 44, 5],
        [1, 5, 6]]
STEPS = 20


@pytest.fixture(scope="module")
def model():
    spec = TransformerSpec(**SPEC_KW)
    return spec, synth_params(spec, q40=False, seed=4, scale=0.3)


def make_engine(model, journal=None, remote=False, temp=0.8,
                kv_quant="f32", **kw):
    spec, params = model
    base = dict(slots=2, temperature=temp, topp=0.9, seed=11,
                prefill_chunk=4, page_size=4, kv_pages=24)
    base.update(kw)
    return ContinuousEngine(spec, params, journal=journal,
                            remote_pages=remote, kv_quant=kv_quant,
                            **base)


def make_pair(model, tmp_path, temp=0.8, kv_quant="f32", channel=None,
              registry=None, chaos=None):
    journal = RequestJournal(str(tmp_path / "prefill.journal"))
    pair = DisaggPair(
        make_engine(model, journal=journal, temp=temp, kv_quant=kv_quant),
        make_engine(model, remote=True, temp=temp, kv_quant=kv_quant),
        channel_host=channel, registry=registry, chaos=chaos)
    return pair, journal


# ------------------------------------------------------------- wire codec


def test_pagewire_roundtrip_f32_and_q8_layouts():
    rng = np.random.default_rng(3)
    for planes in (
            (rng.standard_normal((2, 4, 2, 16)).astype(np.float32),
             rng.standard_normal((2, 4, 2, 16)).astype(np.float32)),
            (rng.integers(-127, 127, (2, 4, 4), dtype=np.int8),
             rng.standard_normal((2, 4, 1)).astype(np.float16),
             rng.integers(-127, 127, (2, 4, 4), dtype=np.int8),
             rng.standard_normal((2, 4, 1)).astype(np.float16))):
        rec = pagewire.encode_record(planes)
        got = pagewire.decode_record(rec)
        assert got is not None and len(got) == len(planes)
        for a, b in zip(planes, got):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert a.tobytes() == b.tobytes()  # byte-exact, not approx
        assert pagewire.record_payload_bytes(rec) == \
            sum(a.nbytes for a in planes)


def test_pagewire_damage_decodes_to_none():
    planes = (np.arange(32, dtype=np.float32).reshape(2, 16),)
    rec = bytearray(pagewire.encode_record(planes))
    # flip a payload byte: CRC must catch it
    corrupt = bytes(rec[:-5]) + bytes([rec[-5] ^ 0xFF]) + bytes(rec[-4:])
    assert pagewire.decode_record(corrupt) is None
    # truncation
    assert pagewire.decode_record(bytes(rec[:-3])) is None
    # garbage
    assert pagewire.decode_record(b"\x00" * 8) is None
    # the original still decodes
    assert pagewire.decode_record(bytes(rec)) is not None


def test_disk_record_bytes_identical_to_wire_blob(tmp_path):
    """The refactor pin (ISSUE 14 satellite): the disk tier's on-disk
    record for a payload is byte-identical to the shared codec's blob —
    the two layouts cannot drift because they are ONE pack."""
    from distributed_llama_tpu.runtime.paging import DiskPageStore

    rng = np.random.default_rng(5)
    payload = (rng.standard_normal((2, 4, 2, 16)).astype(np.float32),
               rng.standard_normal((2, 4, 2, 16)).astype(np.float32))
    store = DiskPageStore(str(tmp_path / "disk"))
    ref = store.store(payload)
    path, off, length, crc, metas = ref
    with open(path, "rb") as fh:
        fh.seek(off)
        disk_bytes = fh.read(length)
    blob, wire_metas = pagewire.pack_planes(payload)
    assert disk_bytes == blob
    assert wire_metas == metas
    # and a load round-trips through the same unpack
    loaded = store.load(ref)
    for a, b in zip(payload, loaded):
        assert a.tobytes() == b.tobytes()


# ------------------------------------------------------ bitwise handoff


@pytest.mark.parametrize("temp", [0.0, 0.8])
@pytest.mark.parametrize("kv_quant", ["f32", "q8"])
def test_handoff_stream_bitwise_vs_single_pool(model, tmp_path, temp,
                                               kv_quant):
    """THE acceptance gate: greedy + seeded-sampled, f32 + q8 pages —
    the two-pool streams equal the single-pool run bit for bit, and
    both pools' page audits close."""
    ref = make_engine(model, temp=temp, kv_quant=kv_quant)
    ref_outs, _ = ref.run(REQS, steps=STEPS)
    pair, journal = make_pair(model, tmp_path, temp=temp,
                              kv_quant=kv_quant)
    outs, summary = pair.run(REQS, steps=STEPS)
    assert outs == ref_outs
    assert summary["shipped"] >= 1
    assert pair.prefill.audit_pages() == []
    assert pair.decode.audit_pages() == []
    pair.close()
    journal.close()


def test_handoff_over_tcp_channel_bitwise(model, tmp_path):
    """Pages genuinely cross the TCP page channel (CRC-verified frames)
    and the streams still match the single-pool run."""
    ref = make_engine(model)
    ref_outs, _ = ref.run(REQS, steps=STEPS)
    reg = Registry()
    pair, journal = make_pair(model, tmp_path, channel="127.0.0.1",
                              registry=reg)
    outs, summary = pair.run(REQS, steps=STEPS)
    assert outs == ref_outs
    assert summary["pages_adopted"] >= 2
    text = reg.expose()
    # both long prompts handed off; their shared 2-page prefix shipped
    # once per handoff (the tree held it for both exports)
    shipped = [ln for ln in text.splitlines()
               if ln.startswith("dllama_dcn_pages_shipped_total")]
    assert shipped and float(shipped[0].split()[-1]) >= 2
    assert 'dllama_handoff_requests_total{verdict="shipped"}' in text
    pair.close()
    journal.close()


def test_handoff_record_wire_roundtrip(model, tmp_path):
    """entry_to_wire/entry_from_wire: the handoff record round-trips
    exactly, and malformed records refuse loudly."""
    pair, journal = make_pair(model, tmp_path)
    stub, may = prefill_stub(REQS[0], STEPS)
    assert may
    pair.prefill.submit(stub)
    pair._drain(pair.prefill)
    assert stub_needs_handoff(stub)
    entry = entry_for_stub(pair.prefill, stub)
    rec = entry_to_wire(entry)
    back = entry_from_wire(rec)
    assert back.replay_tokens == entry.replay_tokens
    assert back.cursor == entry.cursor
    assert back.seed == entry.seed
    assert (back.temperature, back.topp) == (entry.temperature,
                                             entry.topp)
    with pytest.raises(ValueError):
        entry_from_wire({"id": 1, "tokens": []})
    with pytest.raises(ValueError):
        entry_from_wire({"tokens": [1, 2]})
    pair.close()
    journal.close()


def test_journal_less_sampled_handoff_refuses(model):
    """Handing off a sampled stream without a journal must raise — the
    coin cursor lives in the journal, and guessing it would replay
    wrong bytes."""
    eng = make_engine(model, temp=0.8)
    stub, _ = prefill_stub(REQS[0], STEPS)
    eng.submit(stub)
    while eng.step_many(1, quiet=True):
        pass
    with pytest.raises(ValueError, match="journal"):
        entry_for_stub(eng, stub)
    # a GREEDY stub derives its record without one (cursor 0)
    eng2 = make_engine(model, temp=0.0)
    stub2, _ = prefill_stub(REQS[0], STEPS)
    eng2.submit(stub2)
    while eng2.step_many(1, quiet=True):
        pass
    entry = entry_for_stub(eng2, stub2)
    assert entry.cursor == 0
    assert entry.replay_tokens == list(stub2.tokens) + stub2.out[8:]


# ---------------------------------------------------------- edge cases


def test_mid_transfer_cancel_frees_pages_on_both_pools(model, tmp_path):
    """Satellite gate: cancel while pages are mid-flight — the decode
    request retires, adopted-but-unapplied pending nodes drop, and both
    pools' audits close with the decode pool's free count restored."""
    pair, journal = make_pair(model, tmp_path, channel="127.0.0.1")
    free0 = pair.decode.allocator.n_free
    stub, _ = prefill_stub(REQS[0], STEPS)
    pair.prefill.submit(stub)
    pair._drain(pair.prefill)
    h = pair.handoff(stub, STEPS)
    assert h is not None and h.n_pages == 2
    assert len(h.adopted) == 2
    assert all(n.pending for n in h.adopted)
    # cancel BEFORE the decode pool ever steps: the transfer is undone
    pair.cancel(h)
    pair._drain(pair.decode)
    assert h.req.done.is_set() and h.req.cancelled
    assert pair.decode.allocator.n_free == free0
    assert pair.prefill.audit_pages() == []
    assert pair.decode.audit_pages() == []
    pair.close()
    journal.close()


def test_radix_publish_lands_on_decode_pool(model, tmp_path):
    """Satellite gate: after a handoff, the shipped prefix lives in the
    DECODE pool's radix tree — a later same-prefix request hits it there
    (no second shipment of those pages, prefill tokens saved)."""
    pair, journal = make_pair(model, tmp_path)
    a = pair.decode.allocator
    outs, _ = pair.run([REQS[0]], steps=STEPS)
    adopted_first = a.remote_adopted
    assert adopted_first == 2
    # second request, same 2-page prefix, different tail
    outs2, _ = pair.run([REQS[1]], steps=STEPS)
    # no NEW adoptions: the windows were already present on decode
    assert a.remote_adopted == adopted_first
    assert a.prefix_hits >= 1
    assert a.tokens_saved >= 8  # 2 pages x 4 positions re-used
    # and the streams still match fresh single-pool runs
    ref = make_engine(model)
    ref_outs, _ = ref.run([REQS[0], REQS[1]], steps=STEPS)
    assert outs[0] == ref_outs[0] and outs2[0] == ref_outs[1]
    pair.close()
    journal.close()


def test_dropped_page_in_flight_stops_adoption_at_gap(model, tmp_path):
    """A page that never arrives (None slot) stops adoption at the gap —
    the suffix re-derives via prefill and the stream is STILL bitwise
    (CRC-visible damage degrades to recompute, never to wrong bytes)."""
    ref = make_engine(model)
    ref_outs, _ = ref.run([REQS[0]], steps=STEPS)
    pair, journal = make_pair(model, tmp_path)
    stub, _ = prefill_stub(REQS[0], STEPS)
    pair.prefill.submit(stub)
    pair._drain(pair.prefill)
    entry = entry_for_stub(pair.prefill, stub)
    # ship only the FIRST page; the second "never arrived"
    from distributed_llama_tpu.runtime.disagg import export_prefix_pages

    payloads = export_prefix_pages(pair.prefill, stub.tokens)
    planes = [payloads[0], None]
    adopted = pair.decode.allocator.adopt_remote_pages(
        stub.tokens[:8], planes)
    assert len(adopted) == 1
    req = decode_request(entry, STEPS)
    pair.decode.submit(req)
    pair._drain(pair.decode)
    assert req.out == ref_outs[0]
    assert pair.decode.audit_pages() == []
    pair.close()
    journal.close()


def test_remote_ingest_inbox_adopts_on_scheduler_thread(model, tmp_path):
    """ingest_remote (the server path): pages + request queued from a
    foreign thread land via the scheduler's inbox — adoption precedes
    admission, so the prefix hits."""
    ref = make_engine(model)
    ref_outs, _ = ref.run([REQS[0]], steps=STEPS)
    pair, journal = make_pair(model, tmp_path)
    stub, _ = prefill_stub(REQS[0], STEPS)
    pair.prefill.submit(stub)
    pair._drain(pair.prefill)
    entry = entry_for_stub(pair.prefill, stub)
    req = decode_request(entry, STEPS)
    from distributed_llama_tpu.runtime.disagg import export_prefix_pages

    planes = export_prefix_pages(pair.prefill, stub.tokens)
    pair.decode.ingest_remote(stub.tokens[:8], planes, req)
    assert pair.decode._n_outstanding() == 1  # inbox counts as work
    pair._drain(pair.decode)
    assert req.out == ref_outs[0]
    assert pair.decode.allocator.remote_adopted == 2
    pair.close()
    journal.close()


def test_ingest_remote_requires_remote_engine(model):
    eng = make_engine(model)  # remote_pages NOT set
    with pytest.raises(ValueError, match="remote_pages"):
        eng.ingest_remote([1, 2, 3, 4], [], Request(tokens=[1], steps=2))


def test_export_prefix_sync_fulfils_from_scheduler(model):
    """export_prefix_sync answers once the scheduler runs an iteration
    (the POST /prefill thread-safety path)."""
    import threading

    eng = make_engine(model, temp=0.0)
    outs, _ = eng.run([REQS[0]], steps=STEPS)  # publishes prompt pages
    box = {}

    def ask():
        box["planes"] = eng.export_prefix_sync(REQS[0], timeout=10)

    t = threading.Thread(target=ask)
    t.start()
    deadline = 200
    while "planes" not in box and deadline:
        eng.step_many(1, quiet=True)
        deadline -= 1
    t.join(timeout=10)
    assert len(box["planes"]) == 2  # both full prompt pages exported


# -------------------------------------------------- scheduler machinery


def test_slo_priority_pops_interactive_first(model):
    from distributed_llama_tpu.obs.slo import SLOPolicy

    policy = SLOPolicy.serving_default()
    eng = make_engine(model, slo=policy, slo_priority=True, slots=1)
    batch = [Request(tokens=[1, 5 + i, 7], steps=6, slo_class="batch")
             for i in range(3)]
    inter = Request(tokens=[1, 40, 41], steps=6, slo_class="interactive")
    for r in batch:
        eng.submit(r)
    eng.submit(inter)  # submitted LAST, must admit first among queued
    eng.step_many(1, quiet=True)  # admits exactly one (slots=1)
    # the single slot holds the interactive request
    active = [s.req for s in eng._pool if not s.free]
    assert active and active[0] is inter
    while eng.step_many(1, quiet=True):
        pass
    assert all(r.done.is_set() for r in batch + [inter])


def test_slo_priority_requires_policy(model):
    with pytest.raises(ValueError, match="slo_priority"):
        make_engine(model, slo_priority=True)


def test_prefill_hold_parks_at_page_boundary_and_resumes(model):
    """Chunk-boundary preemption: with the hold firing at every
    boundary, a long prefill parks page-aligned, makes one-chunk
    progress per scheduler iteration (masked out of dispatches while
    parked), and the final stream is BITWISE the no-preemption run."""
    long_prompt = [1] + [(7 * j) % 90 + 5 for j in range(24)] + [3]
    ref = make_engine(model, temp=0.0)
    ref_outs, _ = ref.run([long_prompt], steps=30)

    eng = make_engine(model, temp=0.0)
    eng.prefill_hold = lambda slot: True  # park at EVERY boundary
    batch = Request(tokens=list(long_prompt), steps=30)
    eng.submit(batch)
    eng.step_many(1, quiet=True)
    parked = [s for s in eng._pool if not s.free and s.prefill_pending]
    assert parked, "the prefill never parked at a chunk boundary"
    assert parked[0].pos % eng.page_size == 0  # page-aligned park point
    pos0 = parked[0].pos
    eng.step_many(1, quiet=True)  # resume makes chunk progress, parked
    assert parked[0].free or parked[0].pos > pos0
    while eng.step_many(1, quiet=True):
        pass
    assert batch.out == ref_outs[0]
    assert eng.audit_pages() == []
    assert eng.stats.prefill_chunks > 0


def test_prefill_hold_ignored_on_q8_pools(model):
    """A q8 pool quantizes at every scatter: a resumed prompt would
    attend over dequantized earlier positions and drift off the
    single-pass bytes — so the hold is deliberately inert there and the
    stream stays bitwise the no-hold run."""
    long_prompt = [1] + [(7 * j) % 90 + 5 for j in range(24)] + [3]
    ref = make_engine(model, temp=0.0, kv_quant="q8")
    ref_outs, _ = ref.run([long_prompt], steps=30)
    eng = make_engine(model, temp=0.0, kv_quant="q8")
    eng.prefill_hold = lambda slot: True
    batch = Request(tokens=list(long_prompt), steps=30)
    eng.submit(batch)
    eng.step_many(1, quiet=True)
    assert not any(s.prefill_pending for s in eng._pool)  # never parks
    while eng.step_many(1, quiet=True):
        pass
    assert batch.out == ref_outs[0]


def test_make_priority_hold_fires_only_for_lower_ranked_slot(model):
    """The router predicate: a batch slot parks when an interactive
    request waits; an interactive slot never parks for batch."""
    import types

    from distributed_llama_tpu.obs.slo import SLOPolicy

    policy = SLOPolicy.serving_default()
    eng = make_engine(model, slo=policy)
    hold = make_priority_hold(eng, policy)
    with eng._lock:
        eng._queue.append(Request(tokens=[1, 2], steps=4,
                                  slo_class="interactive"))
    batch_slot = types.SimpleNamespace(
        req=Request(tokens=[1, 3], steps=4, slo_class="batch"))
    inter_slot = types.SimpleNamespace(
        req=Request(tokens=[1, 4], steps=4, slo_class="interactive"))
    assert hold(batch_slot)
    assert not hold(inter_slot)
    with eng._lock:
        eng._queue.clear()
    assert not hold(batch_slot)  # nothing waiting: no preemption


def test_remote_pages_requires_paged_engine(model):
    spec, params = model
    with pytest.raises(ValueError, match="remote_pages"):
        ContinuousEngine(spec, params, slots=2, temperature=0.0,
                         topp=0.9, seed=1, remote_pages=True)


# ------------------------------------------------- two-pool virtual sim


def _two_pool_setup(seed=7):
    import argparse

    from loadcheck import (_two_pool_policy, _two_pool_spec,
                           build_engine_factory)
    from loadgen import generate_trace

    args = argparse.Namespace(seed=seed, slots=4, page_size=4,
                              kv_pages=20, spec_k=0, block_steps=1,
                              two_pool_rate=0.25, requests=24,
                              arrivals="bursty")
    make = build_engine_factory(args)
    policy = _two_pool_policy()
    trace = generate_trace(_two_pool_spec(args), seed)
    return make, policy, trace


def test_two_pool_sweep_disagg_beats_colocated():
    """The CI-gated claim: at equal simulated hardware under the mixed
    interactive/batch trace, the disaggregated topology beats the
    colocated baseline on interactive-class SLO attainment."""
    from loadgen import drive_pools

    make, policy, trace = _two_pool_setup()
    slots, pages = 8, 64
    coloc = [make(slo=policy, slo_priority=True, slots=slots,
                  kv_pages=pages) for _ in range(2)]
    res_c = drive_pools(coloc, trace, policy, mode="colocated")
    prefill = make(slo=policy, slo_priority=True, slots=slots,
                   kv_pages=pages)
    prefill.prefill_hold = make_priority_hold(prefill, policy)
    decode = make(remote_pages=True, slots=slots, kv_pages=pages)
    res_d = drive_pools([prefill, decode], trace, policy, mode="disagg")
    assert res_d.attainment["interactive"] > \
        res_c.attainment["interactive"]
    # every pool's page accounting closes after the sweep
    for eng in coloc + [prefill, decode]:
        assert eng.audit_pages() == []
    # the decode pool genuinely adopted shipped pages and took the
    # short-prompt traffic directly (routing)
    assert res_d.engine["pages_adopted"] > 0
    assert res_d.engine["pools"][1]["steps"] > \
        res_d.engine["pools"][0]["steps"]


@pytest.mark.slow
def test_two_pool_sweep_deterministic():
    """Same seed + same trace => identical verdict sets and goodput, run
    to run (the loadcheck CI property extended to two pools). Slow-marked
    (two full sweeps); the fast tier keeps the single-sweep gate above
    and ci.sh runs the real loadcheck gate."""
    from loadgen import drive_pools

    results = []
    for _ in range(2):
        make, policy, trace = _two_pool_setup()
        prefill = make(slo=policy, slo_priority=True, slots=8,
                       kv_pages=64)
        prefill.prefill_hold = make_priority_hold(prefill, policy)
        decode = make(remote_pages=True, slots=8, kv_pages=64)
        res = drive_pools([prefill, decode], trace, policy,
                          mode="disagg")
        results.append((res.verdicts(), res.goodput_tokens,
                        round(res.duration, 6)))
    assert results[0] == results[1]


def test_dcn_budget_prices_pages_per_kv_quant():
    """comm_stats.dcn_handoff_budget: pages x wire bytes, q8 cheaper
    than f32 by the exact PR-11 ratio, partial tail honestly excluded."""
    from distributed_llama_tpu.analysis.memory_model import (
        disagg_pool_model, kv_page_bytes)
    from distributed_llama_tpu.parallel.comm_stats import (
        dcn_handoff_budget, dcn_page_bytes)

    spec = TransformerSpec(**SPEC_KW)
    for kvq in ("f32", "q8"):
        b = dcn_handoff_budget(spec, 1, 10, 4, kv_quant=kvq)
        assert b["pages"] == 2 and b["skipped_positions"] == 2
        per = dcn_page_bytes(spec, 1, 4, kvq)
        assert per == kv_page_bytes(spec, 1, 4, kv_quant=kvq)
        assert b["bytes"] == 2 * per
    f32 = dcn_handoff_budget(spec, 1, 16, 4, kv_quant="f32")["bytes"]
    q8 = dcn_handoff_budget(spec, 1, 16, 4, kv_quant="q8")["bytes"]
    assert f32 / q8 == pytest.approx(128 / 34, rel=1e-6)
    # ... and the page payload a real handoff ships weighs exactly the
    # budgeted bytes (the model and the wire cannot drift)
    eng = make_engine((spec, synth_params(spec, q40=False, seed=4,
                                          scale=0.3)), temp=0.0)
    outs, _ = eng.run([REQS[0]], steps=STEPS)
    from distributed_llama_tpu.runtime.disagg import export_prefix_pages

    payloads = export_prefix_pages(eng, REQS[0])
    assert sum(pagewire.record_payload_bytes(p) for p in payloads) == \
        dcn_handoff_budget(spec, 1, 8, 4)["bytes"]
    model = disagg_pool_model(spec, 1, 24, 24, page_size=4)
    assert model["handoff"]["ship_ms_per_request"] > 0
    assert model["prefill"]["bytes"] == 24 * model["page_bytes"]


def test_modeled_dcn_handoff_ms_scales_with_pages():
    from distributed_llama_tpu.parallel.shard_sim import (
        modeled_dcn_handoff_ms)

    spec = TransformerSpec(**SPEC_KW)
    short = modeled_dcn_handoff_ms(spec, 1, 8, 4)
    long_ = modeled_dcn_handoff_ms(spec, 1, 32, 4)
    assert long_ > short > 0
    # q8 ships cheaper at the same prompt
    assert modeled_dcn_handoff_ms(spec, 1, 32, 4, kv_quant="q8") < long_


# ------------------------------------------------------------ channel


def test_page_channel_resume_and_crc(model):
    """The channel's transfer discipline: unknown handoffs come back
    empty, records survive the trip byte-exact, ACK retires them."""
    from distributed_llama_tpu.runtime.page_channel import (
        PageChannelClient, PageChannelServer)

    rng = np.random.default_rng(9)
    planes = [(rng.standard_normal((2, 4, 2, 16)).astype(np.float32),
               rng.standard_normal((2, 4, 2, 16)).astype(np.float32))
              for _ in range(3)]
    records = [pagewire.encode_record(p) for p in planes]
    server = PageChannelServer()
    try:
        client = PageChannelClient(f"127.0.0.1:{server.port}")
        assert client.fetch("nope") == []
        server.publish("h1", records)
        assert server.queue_depth == 1
        got = client.fetch("h1", len(records))
        assert len(got) == 3
        for orig, back in zip(planes, got):
            for a, b in zip(orig, back):
                assert a.tobytes() == b.tobytes()
        assert server.queue_depth == 0  # acked -> retired
    finally:
        server.close()


# ----------------------------------------------------------- the drill


@pytest.mark.slow
def test_kill_mid_handoff_drill_green_and_mutation_red():
    from distributed_llama_tpu.runtime.chaos import drill_kill_mid_handoff

    res = drill_kill_mid_handoff(None)
    assert res.passed, res.violations
    assert res.details["handoffs_cut"] == 2
    assert res.details["recovered"] == 2
    mutated = drill_kill_mid_handoff(None,
                                     inject={"drop-page-in-flight"})
    assert not mutated.passed
    assert any("diverged" in v for v in mutated.violations)


def test_prejournal_is_the_durability_point(model, tmp_path):
    """The HTTP decode path's crash contract: prejournal lands the admit
    BEFORE any page moves — a 'crash' right after it recovers the
    request; an abandoned prejournal (handoff fell back local) does
    not."""
    jp = str(tmp_path / "decode.journal")
    eng = ContinuousEngine(*model, slots=2, temperature=0.0, topp=0.9,
                           seed=11, prefill_chunk=4, page_size=4,
                           kv_pages=24, remote_pages=True,
                           journal=RequestJournal(jp))
    dreq = Request(tokens=list(REQS[0]), steps=STEPS, temperature=0.0,
                   topp=0.9, seed=501)
    eng.prejournal(dreq)
    assert dreq.prejournaled
    # "crash" before submit: a fresh engine on the same journal recovers it
    eng._journal._fh.close()
    eng2 = ContinuousEngine(*model, slots=2, temperature=0.0, topp=0.9,
                            seed=11, prefill_chunk=4, page_size=4,
                            kv_pages=24, remote_pages=True,
                            journal=RequestJournal(jp))
    assert eng2.recover() == 1
    while eng2.step_many(1, quiet=True):
        pass
    # submit() of a prejournaled request appends NO second admit
    dreq2 = Request(tokens=list(REQS[1]), steps=STEPS, temperature=0.0,
                    topp=0.9, seed=502)
    eng2.prejournal(dreq2)
    before = eng2._journal.records_total
    eng2.submit(dreq2)
    assert eng2._journal.records_total == before
    while eng2.step_many(1, quiet=True):
        pass
    # abandoned prejournal: retired, never recovered
    dreq3 = Request(tokens=list(REQS[0]), steps=STEPS, temperature=0.0,
                    topp=0.9, seed=503)
    eng2.prejournal(dreq3)
    eng2.abandon_prejournaled(dreq3)
    assert eng2._journal.incomplete() == []


def test_page_channel_retention_cap_bounds_the_store():
    from distributed_llama_tpu.runtime.page_channel import (
        PageChannelClient, PageChannelServer)

    planes = (np.arange(16, dtype=np.float32).reshape(4, 4),)
    rec = pagewire.encode_record(planes)
    server = PageChannelServer(retain_max=3)
    try:
        for i in range(5):
            server.publish(f"h{i}", [rec])
        assert server.queue_depth == 3  # oldest two evicted
        assert server.evicted_handoffs == 2
        client = PageChannelClient(f"127.0.0.1:{server.port}")
        assert client.fetch("h0") == []      # evicted: nothing served
        assert len(client.fetch("h4", 1)) == 1
        client.ack("h3")                     # explicit give-up retire
        assert server.queue_depth == 1
    finally:
        server.close()
